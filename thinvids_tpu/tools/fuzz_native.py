"""Corruption/truncation fuzz harness for the native entropy code.

The native CAVLC parsers (`cavlc_unpack_compact`,
`cavlc_sparse_unpack2`) consume bytes that crossed the device→host
link, and `cavlc_pack_islice16` consumes the level arrays they
produce; none of them may ever read or write out of bounds, whatever a
torn transfer hands them. This harness drives all three with valid
payloads, then systematic mutations (byte flips, truncations, garbage
extension, count perturbation), asserting the contract:

- a VALID payload round-trips bit-identically through the native entry
  and the numpy reference (codecs/h264/layout.py);
- a CORRUPT payload either still decodes (both implementations, to the
  SAME levels) or is rejected by both (ValueError / IndexError) —
  never a crash, never a silent native/host divergence;
- the pack direction holds the same bar: the native and pure-Python
  slice packers emit identical NAL bytes on codeable levels and BOTH
  reject uncodeable ones (plus a raw-entry no-crash leg with garbage
  header bits).

Run it under the sanitizer builds to turn "never a crash" into a
machine-checked claim (tests/test_native_fuzz.py, `slow`):

    TVT_NATIVE_SANITIZE=ubsan \
        UBSAN_OPTIONS=halt_on_error=1 python -m thinvids_tpu.tools.fuzz_native
    TVT_NATIVE_SANITIZE=asan ASAN_OPTIONS=detect_leaks=0 \
        LD_PRELOAD=$(g++ -print-file-name=libasan.so) \
        python -m thinvids_tpu.tools.fuzz_native

Deterministic: --seed fixes the whole corpus.
"""

from __future__ import annotations

import argparse

import numpy as np

#: rejections both sides may raise on corrupt input
_REJECT = (ValueError, IndexError)

#: shared count-perturbation corpus — BOTH entries (compact payload
#: and three-array sparse2) must face the same hostile counts
_COUNT_DELTAS = ((1, 0), (-1, 0), (0, 7), (0, -3), (1 << 20, 0),
                 (0, 1 << 20))


def build_valid_case(rng: np.random.Generator):
    """One consistent compact stream: (L, nblk, nval, payload,
    bitmap, bmask16, vals)."""
    NB = int(rng.integers(1, 260))
    L = NB * 16 - int(rng.integers(0, 16))      # ragged tail block
    NB = -(-L // 16)
    nblk = int(rng.integers(0, NB + 1))
    live = np.sort(rng.choice(NB, size=nblk, replace=False))
    bm = np.zeros(NB, np.uint8)
    bm[live] = 1
    bitmap = np.packbits(bm)
    masks = rng.integers(1, 1 << 16, size=nblk, dtype=np.uint32) \
        .astype(np.uint16)
    nval = int(sum(int(m).bit_count() for m in masks))
    vals = rng.integers(-128, 128, size=nval).astype(np.int8)
    payload = np.concatenate([
        bitmap.view(np.uint8),
        np.stack([(masks & 0xFF), (masks >> 8)], axis=1)
        .astype(np.uint8).reshape(-1) if nblk else
        np.zeros(0, np.uint8),
        vals.view(np.uint8)])
    return L, nblk, nval, payload, bitmap, masks, vals


def mutations(rng: np.random.Generator, L, nblk, nval, payload):
    """Corrupt variants of one case: (L, nblk, nval, payload)."""
    out = []
    for _ in range(3):                          # byte flips
        p = payload.copy()
        if p.size:
            i = int(rng.integers(0, p.size))
            p[i] ^= int(rng.integers(1, 256))
        out.append((L, nblk, nval, p))
    out.append((L, nblk, nval,
                payload[:int(rng.integers(0, payload.size + 1))]))
    out.append((L, nblk, nval, np.concatenate(
        [payload, rng.integers(0, 256,
                               size=int(rng.integers(1, 64)))
         .astype(np.uint8)])))
    for dblk, dval in _COUNT_DELTAS + ((-nblk - 1, 0), (0, -nval - 1)):
        out.append((L, nblk + dblk, nval + dval, payload))
    out.append((L + 16, nblk, nval, payload))
    out.append((max(1, L - 16), nblk, nval, payload))
    return out


def run_both_compact(native_mod, layout, L, nblk, nval, payload):
    try:
        got_n = ("ok", native_mod.unpack_compact(nblk, nval, payload, L))
    except _REJECT:
        got_n = ("reject", None)
    try:
        got_h = ("ok", layout.unpack_compact_host(payload, nblk, nval, L))
    except _REJECT:
        got_h = ("reject", None)
    return got_n, got_h


def run_both_sparse2(native_mod, layout, L, nblk, nval, bitmap, masks,
                     vals):
    try:
        got_n = ("ok", native_mod.block_sparse_unpack2(
            nblk, nval, bitmap, masks, vals, L))
    except _REJECT:
        got_n = ("reject", None)
    try:
        got_h = ("ok", layout.block_sparse_unpack2_host(
            nblk, nval, bitmap, masks, vals, L))
    except _REJECT:
        got_h = ("reject", None)
    return got_n, got_h


def fuzz_pack(native_mod, rng: np.random.Generator) -> None:
    """Drive the int16 I-slice packer with hostile level arrays. Two
    contracts, checked on the same arrays:

    - raw entry, garbage header bits: bytes out or a mapped error
      (ValueError for levels CAVLC cannot code, RuntimeError for cap
      overflow) — never UB;
    - full slice (`encoder.pack_slice`): the native and pure-Python
      packers agree — identical NAL bytes, or BOTH reject the levels
      with `ValueError` (bit parity for the pack direction, matching
      what the two unpack entries get above)."""
    from ..codecs.h264.encoder import FrameLevels, pack_slice
    from ..codecs.h264.headers import PPS, SPS

    mbw, mbh = int(rng.integers(1, 5)), int(rng.integers(1, 5))
    nmb = mbw * mbh
    scale = int(rng.choice([8, 512, 4096, 32767]))
    levels = rng.integers(-scale, scale + 1, size=nmb * 384)
    mask = rng.random(nmb * 384) < float(rng.choice([0.02, 0.3, 0.9]))
    flat = np.where(mask, levels, 0).astype(np.int16)
    o = nmb * 16
    luma_dc = flat[:o].reshape(nmb, 16)
    luma_ac = flat[o:o + nmb * 240].reshape(nmb, 16, 15)
    o += nmb * 240
    chroma_dc = flat[o:o + nmb * 8].reshape(nmb, 2, 4)
    chroma_ac = flat[o + nmb * 8:].reshape(nmb, 2, 4, 15)
    modes = rng.integers(0, 4, size=nmb).astype(np.int32)
    try:
        out = native_mod.pack_islice(
            b"\xff\x80", 10, modes, modes % 4, luma_dc, luma_ac,
            chroma_dc, chroma_ac, mbw, mbh)
        assert isinstance(out, bytes)
    except (ValueError, RuntimeError):
        pass                                    # mapped error paths

    fl = FrameLevels(luma_mode=modes, chroma_mode=modes % 4,
                     luma_dc=luma_dc, luma_ac=luma_ac,
                     chroma_dc=chroma_dc, chroma_ac=chroma_ac)
    sps, pps = SPS(width=mbw * 16, height=mbh * 16), PPS(init_qp=27)
    try:
        nat = ("ok", pack_slice(fl, mbw, mbh, sps, pps, 27, native=True))
    except ValueError:
        nat = ("reject", None)
    try:
        py = ("ok", pack_slice(fl, mbw, mbh, sps, pps, 27, native=False))
    except ValueError:
        py = ("reject", None)
    assert nat == py, (
        f"pack parity divergence at {mbw}x{mbh} scale={scale}: "
        f"native={nat[0]} python={py[0]}")


def _check_pair(got_n, got_h, ctx: str):
    """The shared accept/reject + parity contract. Returns (accepted,
    rejected) increments."""
    if got_n[0] == "ok" and got_h[0] == "ok":
        assert np.array_equal(got_n[1], got_h[1]), (
            f"native/host divergence on {ctx}")
        return 1, 0
    # what one side rejects the other must reject too — a native
    # parser that silently accepts what the reference refuses is how
    # corrupt levels reach the packer (and vice versa)
    assert got_n[0] == got_h[0] == "reject", (
        f"accept/reject divergence on {ctx}: native={got_n[0]} "
        f"host={got_h[0]}")
    return 0, 1


def sparse2_mutations(rng: np.random.Generator, L, nblk, nval, bitmap,
                      masks, vals):
    """Corrupt variants for the three-array entry: count perturbation
    (exercises the wrapper bounds validation that keeps hostile counts
    inside the buffers), bitmap bit flips (incl. padding bits), mask
    corruption, and truncated streams."""
    out = []
    for dblk, dval in _COUNT_DELTAS + ((-nblk - 1, 0), (0, -nval - 1)):
        out.append((L, nblk + dblk, nval + dval, bitmap, masks, vals))
    b = bitmap.copy()
    if b.size:
        b[int(rng.integers(0, b.size))] ^= int(rng.integers(1, 256))
    out.append((L, nblk, nval, b, masks, vals))
    m = masks.copy()
    if m.size:
        m[int(rng.integers(0, m.size))] ^= int(rng.integers(1, 1 << 16))
    out.append((L, nblk, nval, bitmap, m, vals))
    out.append((L, nblk, nval, bitmap,
                masks[:int(rng.integers(0, masks.size + 1))], vals))
    out.append((L, nblk, nval, bitmap, masks,
                vals[:int(rng.integers(0, vals.size + 1))]))
    out.append((L, nblk, nval, bitmap[:max(0, bitmap.size - 1)],
                masks, vals))
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="fuzz_native")
    parser.add_argument("--iterations", type=int, default=200)
    parser.add_argument("--seed", type=int, default=20260804)
    args = parser.parse_args(argv)

    from .. import native as native_mod
    from ..codecs.h264 import layout

    if not native_mod.available():
        print("fuzz_native: no compiler / native build failed — "
              "nothing to fuzz")
        return 0

    rng = np.random.default_rng(args.seed)
    cases = accepted = rejected = 0
    for _ in range(args.iterations):
        L, nblk, nval, payload, bitmap, masks, vals = \
            build_valid_case(rng)
        # valid case: both accept, bit-identical
        got_n, got_h = run_both_compact(native_mod, layout, L, nblk,
                                        nval, payload)
        assert got_n[0] == got_h[0] == "ok", "valid payload rejected"
        assert np.array_equal(got_n[1], got_h[1]), \
            "native/host divergence on a VALID payload"
        got_n, got_h = run_both_sparse2(native_mod, layout, L, nblk,
                                        nval, bitmap, masks, vals)
        assert got_n[0] == got_h[0] == "ok"
        assert np.array_equal(got_n[1], got_h[1])

        for mL, mblk, mval, mpayload in mutations(rng, L, nblk, nval,
                                                  payload):
            cases += 1
            pair = run_both_compact(native_mod, layout, mL, mblk,
                                    mval, mpayload)
            a, r = _check_pair(*pair,
                               ctx=f"compact L={mL} nblk={mblk} "
                                   f"nval={mval}")
            accepted += a
            rejected += r
        for mcase in sparse2_mutations(rng, L, nblk, nval, bitmap,
                                       masks, vals):
            cases += 1
            pair = run_both_sparse2(native_mod, layout, *mcase)
            a, r = _check_pair(*pair,
                               ctx=f"sparse2 L={mcase[0]} "
                                   f"nblk={mcase[1]} nval={mcase[2]}")
            accepted += a
            rejected += r
        fuzz_pack(native_mod, rng)
    print(f"fuzz_native: {args.iterations} valid cases, {cases} "
          f"mutations ({accepted} accepted, {rejected} rejected), "
          f"0 crashes, 0 divergences")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
