"""H.264 parameter sets and slice headers (§7.3.2, §7.3.3).

Baseline profile, progressive, 4:2:0, one slice per picture, CAVLC,
pic_order_cnt_type=2 (display order == decode order — true for the
intra/IPPP streams this codec emits), deblocking disabled via the slice
header so encoder reconstruction is exactly what decoders output.
"""

from __future__ import annotations

import dataclasses

from ...io.bits import BitReader, BitWriter, annexb_nal

NAL_SLICE_IDR = 5
NAL_SEI = 6
NAL_SPS = 7
NAL_PPS = 8
NAL_SLICE_NON_IDR = 1

SLICE_TYPE_P = 0
SLICE_TYPE_I = 2


@dataclasses.dataclass(frozen=True)
class SPS:
    width: int                     # luma samples, pre-crop display width
    height: int
    profile_idc: int = 66          # baseline
    level_idc: int = 40
    log2_max_frame_num: int = 8
    num_ref_frames: int = 1
    fps_num: int = 30
    fps_den: int = 1

    def __post_init__(self):
        if self.width % 2 or self.height % 2:
            # 4:2:0 frame cropping offsets are in 2-luma-pixel units, so
            # an odd display dimension cannot be represented — callers
            # must pre-scale to even dimensions. Validated here (not in
            # to_rbsp) so encoders fail fast at construction.
            raise ValueError(
                f"odd dimensions {self.width}x{self.height} are not "
                "representable with 4:2:0 frame cropping")

    @property
    def mb_width(self) -> int:
        return (self.width + 15) // 16

    @property
    def mb_height(self) -> int:
        return (self.height + 15) // 16

    def to_rbsp(self) -> bytes:
        bw = BitWriter()
        bw.write(self.profile_idc, 8)
        # constraint_set0..5 + reserved: set0/set1 for baseline compat
        bw.write(0b11000000, 8)
        bw.write(self.level_idc, 8)
        bw.ue(0)                               # seq_parameter_set_id
        bw.ue(self.log2_max_frame_num - 4)     # log2_max_frame_num_minus4
        bw.ue(2)                               # pic_order_cnt_type
        bw.ue(self.num_ref_frames)             # max_num_ref_frames
        bw.write_bit(0)                        # gaps_in_frame_num_allowed
        bw.ue(self.mb_width - 1)
        bw.ue(self.mb_height - 1)              # map units (frame_mbs_only)
        bw.write_bit(1)                        # frame_mbs_only_flag
        bw.write_bit(1)                        # direct_8x8_inference_flag
        crop_r = (self.mb_width * 16 - self.width) // 2
        crop_b = (self.mb_height * 16 - self.height) // 2
        if crop_r or crop_b:
            bw.write_bit(1)
            bw.ue(0)          # left
            bw.ue(crop_r)     # right (units of SubWidthC=2)
            bw.ue(0)          # top
            bw.ue(crop_b)     # bottom (units of SubHeightC*(2-fmof)=2)
        else:
            bw.write_bit(0)
        # VUI with timing so probes report fps
        bw.write_bit(1)                        # vui_parameters_present
        bw.write_bit(0)                        # aspect_ratio_info_present
        bw.write_bit(0)                        # overscan_info_present
        bw.write_bit(0)                        # video_signal_type_present
        bw.write_bit(0)                        # chroma_loc_info_present
        bw.write_bit(1)                        # timing_info_present
        bw.write(self.fps_den, 32)             # num_units_in_tick
        bw.write(self.fps_num * 2, 32)         # time_scale (field rate)
        bw.write_bit(1)                        # fixed_frame_rate_flag
        bw.write_bit(0)                        # nal_hrd_parameters_present
        bw.write_bit(0)                        # vcl_hrd_parameters_present
        bw.write_bit(0)                        # pic_struct_present
        bw.write_bit(0)                        # bitstream_restriction
        bw.rbsp_trailing_bits()
        return bw.getvalue()

    def to_nal(self) -> bytes:
        return annexb_nal(3, NAL_SPS, self.to_rbsp())

    @classmethod
    def parse_rbsp(cls, rbsp: bytes) -> "SPS":
        br = BitReader(rbsp)
        profile = br.read(8)
        br.read(8)  # constraint flags
        level = br.read(8)
        br.ue()     # sps id
        if profile in (100, 110, 122, 244, 44, 83, 86, 118, 128):
            chroma = br.ue()
            if chroma == 3:
                br.read_bit()
            br.ue()
            br.ue()
            br.read_bit()
            if br.read_bit():  # seq_scaling_matrix_present
                raise ValueError("scaling matrices not supported")
        log2_mfn = br.ue() + 4
        poc_type = br.ue()
        if poc_type == 0:
            br.ue()
        elif poc_type == 1:
            br.read_bit()
            br.se()
            br.se()
            for _ in range(br.ue()):
                br.se()
        num_ref = br.ue()
        br.read_bit()
        mbw = br.ue() + 1
        mbh_units = br.ue() + 1
        fmof = br.read_bit()
        mbh = mbh_units * (1 if fmof else 2)
        if not fmof:
            br.read_bit()  # mb_adaptive_frame_field
        br.read_bit()  # direct_8x8_inference
        width, height = mbw * 16, mbh * 16
        if br.read_bit():  # cropping
            cl, cr, ct, cb = br.ue(), br.ue(), br.ue(), br.ue()
            width -= 2 * (cl + cr)
            height -= (2 if fmof else 4) * (ct + cb)
        fps_num, fps_den = 30, 1
        if br.read_bit():  # vui present
            if br.read_bit():  # aspect ratio
                if br.read(8) == 255:
                    br.read(32)
            if br.read_bit():
                br.read_bit()  # overscan
            if br.read_bit():  # video signal type
                br.read(3)
                br.read_bit()
                if br.read_bit():
                    br.read(24)
            if br.read_bit():  # chroma loc
                br.ue()
                br.ue()
            if br.read_bit():  # timing
                fps_den = br.read(32)
                fps_num = br.read(32) // 2 or 30
        return cls(width=width, height=height, profile_idc=profile,
                   level_idc=level, log2_max_frame_num=log2_mfn,
                   num_ref_frames=num_ref, fps_num=fps_num, fps_den=fps_den)


@dataclasses.dataclass(frozen=True)
class PPS:
    init_qp: int = 26
    deblocking_control_present: bool = True

    def to_rbsp(self) -> bytes:
        bw = BitWriter()
        bw.ue(0)             # pic_parameter_set_id
        bw.ue(0)             # seq_parameter_set_id
        bw.write_bit(0)      # entropy_coding_mode (CAVLC)
        bw.write_bit(0)      # bottom_field_pic_order_in_frame_present
        bw.ue(0)             # num_slice_groups_minus1
        bw.ue(0)             # num_ref_idx_l0_default_active_minus1
        bw.ue(0)             # num_ref_idx_l1_default_active_minus1
        bw.write_bit(0)      # weighted_pred_flag
        bw.write(0, 2)       # weighted_bipred_idc
        bw.se(self.init_qp - 26)   # pic_init_qp_minus26
        bw.se(0)             # pic_init_qs_minus26
        bw.se(0)             # chroma_qp_index_offset
        bw.write_bit(1 if self.deblocking_control_present else 0)
        bw.write_bit(0)      # constrained_intra_pred_flag
        bw.write_bit(0)      # redundant_pic_cnt_present
        bw.rbsp_trailing_bits()
        return bw.getvalue()

    def to_nal(self) -> bytes:
        return annexb_nal(3, NAL_PPS, self.to_rbsp())

    @classmethod
    def parse_rbsp(cls, rbsp: bytes) -> "PPS":
        br = BitReader(rbsp)
        br.ue()
        br.ue()
        if br.read_bit():
            raise ValueError("CABAC streams not supported")
        br.read_bit()
        if br.ue() != 0:
            raise ValueError("slice groups not supported")
        br.ue()
        br.ue()
        br.read_bit()
        br.read(2)
        init_qp = br.se() + 26
        br.se()
        chroma_qp_off = br.se()
        if chroma_qp_off != 0:
            raise ValueError("chroma_qp_index_offset != 0 not supported")
        dbc = bool(br.read_bit())
        if br.read_bit():
            raise ValueError("constrained_intra_pred not supported")
        br.read_bit()
        return cls(init_qp=init_qp, deblocking_control_present=dbc)


@dataclasses.dataclass(frozen=True)
class SliceHeader:
    slice_type: int                 # SLICE_TYPE_I / SLICE_TYPE_P
    frame_num: int
    idr: bool
    qp: int
    idr_pic_id: int = 0
    first_mb: int = 0
    #: disable_deblocking_filter_idc (§7.4.3): 1 = off (the historical
    #: default — encoder recon needs no filter), 0 = §8.7 in-loop
    #: deblocking across the whole picture (the `deblock` RD feature),
    #: 2 = filter inside slices only (parsed, but neither emitted by
    #: this encoder nor decoded by the in-repo decoder).
    deblock_idc: int = 1

    @property
    def disable_deblocking(self) -> bool:
        return self.deblock_idc == 1

    def write(self, bw: BitWriter, sps: SPS, pps: PPS) -> None:
        bw.ue(self.first_mb)
        # +5 variant: all slices of this picture share the type
        bw.ue(self.slice_type + 5)
        bw.ue(0)                                        # pps id
        bw.write(self.frame_num % (1 << sps.log2_max_frame_num),
                 sps.log2_max_frame_num)
        if self.idr:
            bw.ue(self.idr_pic_id)
        if self.slice_type == SLICE_TYPE_P:
            bw.write_bit(0)      # num_ref_idx_active_override_flag
            bw.write_bit(0)      # ref_pic_list_modification_flag_l0
        if self.idr:
            bw.write_bit(0)      # no_output_of_prior_pics
            bw.write_bit(0)      # long_term_reference_flag
        elif self.slice_type == SLICE_TYPE_P:
            bw.write_bit(0)      # adaptive_ref_pic_marking_mode_flag
        bw.se(self.qp - pps.init_qp)                    # slice_qp_delta
        if pps.deblocking_control_present:
            bw.ue(self.deblock_idc)          # disable_deblocking_filter_idc
            if self.deblock_idc != 1:
                bw.se(0)                     # slice_alpha_c0_offset_div2
                bw.se(0)                     # slice_beta_offset_div2

    @classmethod
    def parse(cls, br: BitReader, sps: SPS, pps: PPS, nal_type: int,
              nal_ref_idc: int) -> "SliceHeader":
        first_mb = br.ue()
        st = br.ue()
        if st >= 5:
            st -= 5
        if st not in (SLICE_TYPE_I, SLICE_TYPE_P):
            raise ValueError(f"unsupported slice type {st}")
        br.ue()  # pps id
        frame_num = br.read(sps.log2_max_frame_num)
        idr = nal_type == NAL_SLICE_IDR
        idr_pic_id = br.ue() if idr else 0
        if st == SLICE_TYPE_P:
            if br.read_bit():               # num_ref_idx_active_override
                br.ue()
            if br.read_bit():               # ref_pic_list_modification_l0
                raise ValueError("ref pic list modification not supported")
        if nal_ref_idc != 0:
            if idr:
                br.read_bit()
                br.read_bit()
            elif st == SLICE_TYPE_P:
                if br.read_bit():
                    raise ValueError("adaptive ref marking not supported")
        qp = pps.init_qp + br.se()
        idc = 1
        if pps.deblocking_control_present:
            idc = br.ue()
            if idc != 1:
                off_a, off_b = br.se(), br.se()
                if off_a or off_b:
                    raise ValueError(
                        "nonzero deblock filter offsets not supported")
        return cls(slice_type=st, frame_num=frame_num, idr=idr, qp=qp,
                   idr_pic_id=idr_pic_id, first_mb=first_mb,
                   deblock_idc=idc)
