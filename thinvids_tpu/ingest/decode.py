"""Input decode: turn a media file into YUV frames for the encode mesh.

The reference transcoded arbitrary compressed sources by delegating
decode to ffmpeg inside each worker's encode command
(/root/reference/worker/tasks.py:1354-1737); here decode is a STREAMING
ingest stage: :func:`open_video` returns a :class:`FrameSource` that
decodes on demand — raw .y4m frames seek in O(1) (fixed-size records,
io/y4m.Y4MRangeReader), .mp4 (AVC) demuxes natively (io/mp4.demux_mp4)
and decodes closed-GOP sample ranges through the bound libavcodec
(tools/oracle) — so an encode never materializes a whole clip in host
RAM, time-to-first-wave is one wave's decode, and a remote worker
decodes only its shard's frame range. The source's audio track rides
along for bit-exact passthrough into the transcoded output.

:func:`read_video` (the old list-materializing API) survives for
small-clip tools and tests; the executors stream through
:func:`open_video` (guarded by tests/test_streaming.py).
"""

from __future__ import annotations

import os
from typing import Iterator

from ..core.types import Frame, VideoMeta
from ..io.mp4 import Mp4Track


class DecodeError(ValueError):
    """File cannot be decoded into frames."""


class FrameSource:
    """Lazy, seekable frame access to one media file.

    Duck-typed as a read-only sequence of :class:`Frame`: ``len(src)``,
    iteration, integer indexing, and contiguous slicing (``src[a:b]``
    is a lazy :class:`_FrameWindow` that decodes only ``[a, b)`` when
    iterated) all work, so the encoder and executors are agnostic
    between a materialized ``list[Frame]`` and a stream.

    ``frames_decoded`` counts frames actually decoded (including any
    mp4 keyframe lead-in) — the bounded-work instrumentation the
    shard-range and residency tests assert on.
    """

    meta: VideoMeta
    audio: Mp4Track | None = None

    def __init__(self) -> None:
        self.frames_decoded = 0

    # -- subclass surface ----------------------------------------------

    def iter_frames(self, start: int = 0,
                    stop: int | None = None) -> Iterator[Frame]:
        """Yield frames [start, stop) decoding only what the range
        needs. Restartable: every call opens its own decode cursor."""
        raise NotImplementedError

    def close(self) -> None:
        """Release any persistent resources (sources keep no open file
        handles between iterations, so this is best-effort hygiene)."""

    # -- sequence protocol ---------------------------------------------

    def __len__(self) -> int:
        return int(self.meta.num_frames)

    def __iter__(self) -> Iterator[Frame]:
        return self.iter_frames()

    def read_range(self, start: int, count: int) -> list[Frame]:
        return list(self.iter_frames(start, start + count))

    def __getitem__(self, key):
        if isinstance(key, slice):
            if key.step not in (None, 1):
                raise ValueError("FrameSource slices must be contiguous")
            start, stop, _ = key.indices(len(self))
            return _FrameWindow(self, start, stop)
        idx = key if key >= 0 else len(self) + key
        frames = self.read_range(idx, 1)
        if not frames:
            raise IndexError(key)
        return frames[0]

    def __enter__(self) -> "FrameSource":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _FrameWindow:
    """Contiguous lazy view into a FrameSource (``src[a:b]``): decodes
    only its own range when iterated, so a remote worker's shard slice
    is O(shard) work and memory instead of O(clip)."""

    def __init__(self, source: FrameSource, start: int, stop: int) -> None:
        self._source = source
        self._start = start
        self._stop = max(start, stop)

    def __len__(self) -> int:
        return self._stop - self._start

    def iter_frames(self, start: int = 0,
                    stop: int | None = None) -> Iterator[Frame]:
        lo = self._start + max(0, start)
        hi = self._stop if stop is None else min(self._stop,
                                                 self._start + stop)
        return self._source.iter_frames(lo, hi)

    def __iter__(self) -> Iterator[Frame]:
        return self.iter_frames()

    def __getitem__(self, key):
        if isinstance(key, slice):
            if key.step not in (None, 1):
                raise ValueError("FrameSource slices must be contiguous")
            start, stop, _ = key.indices(len(self))
            return _FrameWindow(self._source, self._start + start,
                                self._start + stop)
        idx = key if key >= 0 else len(self) + key
        if not 0 <= idx < len(self):
            raise IndexError(key)
        return self._source[self._start + idx]


class _Y4MFrameSource(FrameSource):
    """Raw y4m: fixed-size frame records → O(1) byte seek per frame."""

    def __init__(self, path: str) -> None:
        super().__init__()
        from ..io.y4m import Y4MRangeReader

        self._reader = Y4MRangeReader(path)
        self.meta = self._reader.meta
        self.audio = None

    def iter_frames(self, start: int = 0,
                    stop: int | None = None) -> Iterator[Frame]:
        stop = len(self) if stop is None else min(stop, len(self))
        for frame in self._reader.read_range(max(0, start), stop):
            self.frames_decoded += 1
            yield frame


class _Mp4FrameSource(FrameSource):
    """AVC .mp4: the demuxed COMPRESSED samples stay in RAM; decode
    happens per closed-GOP sample range through the bound libavcodec,
    so resident decoded frames are bounded by one GOP + the consumer's
    window rather than the whole clip, and a range read decodes only
    from the nearest preceding sync sample (the keyframe lead-in)."""

    def __init__(self, path: str) -> None:
        super().__init__()
        from ..io.mp4 import read_mp4
        from ..tools import oracle

        if not oracle.oracle_available():
            raise DecodeError(
                "mp4 input needs the libavcodec decoder, which is "
                "unavailable in this environment")
        self._oracle = oracle
        m = read_mp4(path)
        self._media = m
        num, den = m.fps
        self.meta = VideoMeta(
            width=m.width, height=m.height, fps_num=num, fps_den=den,
            num_frames=m.num_frames, codec="h264",
            duration_s=m.duration_ts / max(1, m.timescale),
            size_bytes=os.path.getsize(path))
        self.audio = m.audio
        self._keys = m.sync_samples()

    def iter_frames(self, start: int = 0,
                    stop: int | None = None) -> Iterator[Frame]:
        import bisect

        n = len(self)
        stop = n if stop is None else min(stop, n)
        w, h = self.meta.width, self.meta.height
        pos = max(0, start)
        while pos < stop:
            ki = bisect.bisect_right(self._keys, pos) - 1
            k = self._keys[ki]
            k_next = self._keys[ki + 1] if ki + 1 < len(self._keys) else n
            planes = self._oracle.decode_h264(
                self._media.annexb_for(k, k_next))
            self.frames_decoded += len(planes)
            if len(planes) != k_next - k:
                raise DecodeError(
                    f"decoded {len(planes)} frames for sample range "
                    f"[{k}, {k_next}), container says {k_next - k}")
            for i in range(pos, min(stop, k_next)):
                y, u, v = planes[i - k]
                yield Frame(y=y[:h, :w], u=u[:h // 2, :w // 2],
                            v=v[:h // 2, :w // 2], pts=i)
            pos = k_next


_SOURCES = {
    ".y4m": _Y4MFrameSource,
    ".mp4": _Mp4FrameSource,
}


def open_video(path: str | os.PathLike) -> FrameSource:
    """Open a media file for streaming decode: parses the header /
    demuxes the container but decodes NO frames yet.

    Raises :class:`DecodeError` for unsupported extensions or
    unreadable content. Supported extensions: `supported_exts()`.
    """
    path = os.fspath(path)
    ext = os.path.splitext(path)[1].lower()
    factory = _SOURCES.get(ext)
    if factory is None:
        raise DecodeError(f"unsupported media extension {ext!r}: {path}")
    try:
        return factory(path)
    except DecodeError:
        raise
    except (OSError, ValueError, EOFError) as exc:
        raise DecodeError(f"cannot decode {path}: {exc}") from exc


def read_video(path: str | os.PathLike
               ) -> tuple[VideoMeta, list[Frame], Mp4Track | None]:
    """(meta, frames, audio_track_or_None), fully MATERIALIZED.

    Kept for small-clip tools (stamping, import, tests); the executors
    and worker daemons stream through :func:`open_video` instead so a
    long clip never pins its decoded frames in RAM at once.
    """
    path = os.fspath(path)
    with open_video(path) as src:
        try:
            return src.meta, src.read_range(0, len(src)), src.audio
        except DecodeError:
            raise
        except (OSError, ValueError, EOFError) as exc:
            raise DecodeError(f"cannot decode {path}: {exc}") from exc


def supported_exts() -> tuple[str, ...]:
    return tuple(_SOURCES)
