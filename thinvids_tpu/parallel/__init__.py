"""Sequence (GOP) parallelism over a TPU device mesh.

The reference shards the video timeline into ~10 MB file segments dispatched
to worker nodes over a task queue (/root/reference/worker/tasks.py:597-609,
977-1052); here the timeline is sharded at closed-GOP boundaries across the
devices of a `jax.sharding.Mesh` with `shard_map`, and encoded segments are
re-assembled in index order (the stitcher analog, tasks.py:2047-2069).

Imports are lazy: the process-based pack sidecars (packproc.py) live in
this package but run in spawned children that must import it WITHOUT
dragging dispatch's jax dependency in (initializing a device backend in
every pack worker would be fatal on real hardware).
"""

__all__ = ["plan_segments", "GopShardEncoder", "encode_clip_sharded"]


def __getattr__(name):
    if name == "plan_segments":
        from .planner import plan_segments

        return plan_segments
    if name in ("GopShardEncoder", "encode_clip_sharded"):
        from . import dispatch

        return getattr(dispatch, name)
    raise AttributeError(name)
