"""HTTP API: the JSON control surface over the coordinator.

Mirrors the reference manager's Flask route surface
(/root/reference/manager/app.py:1919-2400) on the stdlib http.server —
no framework dependency, same contracts.
"""

from .server import ApiServer

__all__ = ["ApiServer"]
