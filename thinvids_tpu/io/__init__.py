"""Container / bitstream IO.

The reference leans on ffmpeg/ffprobe for every byte of container work
(/root/reference/worker/tasks.py:190-268, manager/app.py:2120-2220). This
package is the from-scratch replacement: raw bit/NAL primitives, YUV4MPEG2
(y4m) frame IO, Annex-B elementary streams, and a pure-Python probe.
"""

from .bits import BitReader, BitWriter, annexb_nal, ebsp_to_rbsp, rbsp_to_ebsp
from .y4m import Y4MReader, Y4MWriter, read_y4m, write_y4m

__all__ = [
    "BitReader",
    "BitWriter",
    "annexb_nal",
    "ebsp_to_rbsp",
    "rbsp_to_ebsp",
    "Y4MReader",
    "Y4MWriter",
    "read_y4m",
    "write_y4m",
]
