"""GOP segment planner — the parts-planner math, TPU-shaped.

Port of the reference's two-step plan (/root/reference/worker/tasks.py:
597-609 and 1019-1031): pick a target shard size, derive the shard count,
then round the count UP to a multiple of the usable worker count so every
dispatch wave fills the farm. Here "workers" are mesh devices and the unit
is frames (closed GOPs), not bytes: a GOP boundary is the only place an
H.26x stream can be cut without cross-shard prediction.
"""

from __future__ import annotations

import dataclasses
import math

from ..core.types import BandPlan, BandSpec, GopSpec, SegmentPlan


def plan_segments(num_frames: int, gop_frames: int, num_devices: int,
                  max_segments: int = 200) -> SegmentPlan:
    """Plan closed-GOP shards for `num_frames` over `num_devices`.

    - `gop_frames` is the TARGET GOP length (the ~10 MB analog).
    - The GOP count is rounded up to a multiple of `num_devices` (when that
      doesn't push GOPs below 1 frame), mirroring the reference's wave
      balancing; bounded by `max_segments`.
    - Every frame is covered exactly once; all GOPs are closed (IDR-led).
    """
    if num_frames <= 0:
        raise ValueError("num_frames must be positive")
    if gop_frames <= 0 or num_devices <= 0:
        raise ValueError("gop_frames and num_devices must be positive")

    n = math.ceil(num_frames / gop_frames)
    # Round up to fill waves — only useful when there's at least one frame
    # per shard; tiny clips keep their natural count.
    rounded = math.ceil(n / num_devices) * num_devices
    if rounded <= num_frames:
        n = rounded
    n = min(n, max_segments, num_frames)

    base = num_frames // n
    extra = num_frames % n          # first `extra` GOPs get one more frame
    gops = []
    start = 0
    for i in range(n):
        length = base + (1 if i < extra else 0)
        gops.append(GopSpec(index=i, start_frame=start, num_frames=length))
        start += length
    assert start == num_frames
    return SegmentPlan(gops=tuple(gops), num_devices=num_devices,
                       frames_per_gop=gop_frames)


def plan_fixed_segments(num_frames: int, gop_frames: int,
                        num_devices: int = 1) -> SegmentPlan:
    """Fixed GOP grid: exactly `gop_frames` per GOP (short tail at the
    end), indices from 0 — boundaries a pure function of the frame
    index, never of mesh width or batch size. The live pipeline pins
    its part boundaries with this (cluster/executor._run_live) and the
    split-frame-encoding path pins its latency-ordered GOP walk
    (parallel/dispatch.SfeShardEncoder), where the mesh parallelizes
    WITHIN a frame and must not reshape the GOP grid."""
    if num_frames <= 0:
        raise ValueError("num_frames must be positive")
    if gop_frames <= 0:
        raise ValueError("gop_frames must be positive")
    gops = []
    start = 0
    while start < num_frames:
        n = min(gop_frames, num_frames - start)
        gops.append(GopSpec(index=len(gops), start_frame=start,
                            num_frames=n))
        start += n
    return SegmentPlan(gops=tuple(gops), num_devices=num_devices,
                       frames_per_gop=gop_frames)


def plan_bands(mb_height: int, mb_width: int, num_bands: int) -> BandPlan:
    """Pin the split-frame-encoding band layout for one job.

    Each of the (at most) `num_bands` devices owns an EQUAL
    `band_mb_rows = ceil(mb_height / num_bands)` MB-row shard — equal
    shapes are a shard_map requirement — and entropy-codes only its
    REAL rows. When `band_mb_rows` covers `mb_height` in fewer than
    `num_bands` bands (short frames on wide meshes), the plan shrinks
    to the bands that hold at least one real MB row: a fully-padded
    band would have no real edge row to source halo pixels from, and
    its device would only ever encode discarded rows.

    Boundaries are MB-aligned by construction and a pure function of
    (mb_height, num_bands): the slice layout of a stream never depends
    on which frame or wave is being encoded.
    """
    if mb_height <= 0 or mb_width <= 0:
        raise ValueError("mb_height and mb_width must be positive")
    if num_bands <= 0:
        raise ValueError("num_bands must be positive")
    rows = math.ceil(mb_height / num_bands)
    n = math.ceil(mb_height / rows)          # bands with >= 1 real row
    bands = []
    for i in range(n):
        start = i * rows
        bands.append(BandSpec(index=i, start_mb_row=start,
                              mb_rows=min(rows, mb_height - start)))
    assert bands[-1].end_mb_row == mb_height
    return BandPlan(bands=tuple(bands), band_mb_rows=rows,
                    mb_width=mb_width)


def plan_band_groups(num_bands: int, groups: int
                     ) -> tuple[tuple[int, int], ...]:
    """Partition a band layout into `groups` contiguous [lo, hi)
    slices — one per band shard / worker host (cluster/remote.py farm
    SFE). Near-equal sizes, first slices take the remainder; a pure
    function of (num_bands, groups) so a crash-resumed plan (and every
    peer's descriptor) reproduces the identical partition."""
    if num_bands <= 0:
        raise ValueError("num_bands must be positive")
    groups = max(1, min(int(groups), num_bands))
    base, extra = divmod(num_bands, groups)
    out = []
    lo = 0
    for i in range(groups):
        n = base + (1 if i < extra else 0)
        out.append((lo, lo + n))
        lo += n
    assert lo == num_bands
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class EncodePlan:
    """The unified, shape-tagged shard plan record every encode path
    keys off (the collapse of the GopShardEncoder / SfeShardEncoder /
    LadderShardEncoder dispatch seams): `shape` picks the executor
    form, `segments` pins the GOP grid, and the band fields pin the
    cross-host SFE layout when `shape == "band"`. The record is pure
    data — JSON-able via `record()` so the durable board checkpoint
    (cluster/partstore.py) can journal it and a crash-resumed
    coordinator re-plans deterministically from the record, never from
    the live farm width."""

    shape: str                        # "gop" | "band"
    segments: SegmentPlan
    total_bands: int = 0              # band shape: global layout width
    halo_rows: int = 0                # band shape: pinned halo depth
    band_groups: tuple[tuple[int, int], ...] = ()

    def record(self) -> dict:
        return {
            "shape": self.shape,
            "total_bands": int(self.total_bands),
            "halo_rows": int(self.halo_rows),
            "band_groups": [[int(lo), int(hi)]
                            for lo, hi in self.band_groups],
        }


def plan_encode(num_frames: int, settings, *, num_devices: int,
                shape: str | None = None, total_bands: int = 0,
                group_count: int = 1, mb_height: int = 0) -> EncodePlan:
    """Build the unified plan for one job. `shape=None` resolves from
    settings (`sfe_bands > 0` → band shape); the band shape uses the
    SFE fixed GOP grid (boundaries a pure function of the frame count,
    never of mesh or farm width) and partitions `total_bands` over
    `group_count` shards."""
    gop_frames = int(settings.gop_frames)
    max_segments = int(settings.max_segments)
    if shape is None:
        shape = "band" if int(settings.get("sfe_bands", 0) or 0) > 0 \
            else "gop"
    if shape == "gop":
        return EncodePlan(
            shape="gop",
            segments=plan_segments(num_frames, gop_frames, num_devices,
                                   max_segments))
    if shape != "band":
        raise ValueError(f"unknown plan shape {shape!r}")
    # the SFE grid: honor max_segments by growing the GOP once up
    # front (SfeShardEncoder.plan's cap semantics)
    gop = max(gop_frames, -(-num_frames // max(1, max_segments)))
    bands = plan_bands(max(1, mb_height), 1, max(1, total_bands))
    groups = plan_band_groups(bands.num_bands, group_count)
    halo = int(settings.get("sfe_halo_rows", 32) or 32)
    return EncodePlan(
        shape="band",
        segments=plan_fixed_segments(num_frames, gop, num_devices),
        total_bands=bands.num_bands,
        halo_rows=max(16, (halo // 16) * 16),
        band_groups=groups)
