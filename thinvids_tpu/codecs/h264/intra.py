"""Intra prediction (H.264 §8.3) and shared macroblock reconstruction.

I16x16 luma modes (0=V, 1=H, 2=DC, 3=plane) and 8x8 chroma modes
(0=DC, 1=H, 2=V, 3=plane). The same reconstruction routines serve the
encoder (closed loop) and the decoder, so encoder recon is by construction
what a conformant decoder produces (deblocking disabled).
"""

from __future__ import annotations

import numpy as np

from .transform import (
    chroma_dc_dequant,
    dequant_4x4,
    inverse_4x4,
    inverse_zigzag,
    luma_dc_dequant,
)

# Luma 4x4 block z-scan order within a MB: (x, y) block coords.
LUMA_BLOCK_ORDER: list[tuple[int, int]] = [
    (0, 0), (1, 0), (0, 1), (1, 1),
    (2, 0), (3, 0), (2, 1), (3, 1),
    (0, 2), (1, 2), (0, 3), (1, 3),
    (2, 2), (3, 2), (2, 3), (3, 3),
]
# Raster order of the 2x2 luma-DC layout is separate: DC coeff (x,y) of
# block grid is scanned zig-zag as a 4x4 "block" itself.

CHROMA_BLOCK_ORDER: list[tuple[int, int]] = [(0, 0), (1, 0), (0, 1), (1, 1)]

LUMA_V, LUMA_H, LUMA_DC, LUMA_PLANE = 0, 1, 2, 3
CHROMA_DC, CHROMA_H, CHROMA_V, CHROMA_PLANE = 0, 1, 2, 3


def predict_luma16(mode: int, top: np.ndarray | None, left: np.ndarray | None,
                   topleft: int | None) -> np.ndarray:
    """16x16 luma prediction. `top`/`left` are length-16 uint8 vectors of
    reconstructed neighbors (None when unavailable)."""
    if mode == LUMA_V:
        if top is None:
            raise ValueError("vertical prediction requires top neighbors")
        return np.tile(top.astype(np.uint8), (16, 1))
    if mode == LUMA_H:
        if left is None:
            raise ValueError("horizontal prediction requires left neighbors")
        return np.tile(left.astype(np.uint8)[:, None], (1, 16))
    if mode == LUMA_DC:
        if top is not None and left is not None:
            dc = (int(top.sum()) + int(left.sum()) + 16) >> 5
        elif left is not None:
            dc = (int(left.sum()) + 8) >> 4
        elif top is not None:
            dc = (int(top.sum()) + 8) >> 4
        else:
            dc = 128
        return np.full((16, 16), dc, np.uint8)
    if mode == LUMA_PLANE:
        if top is None or left is None or topleft is None:
            raise ValueError("plane prediction requires top+left+corner")
        t = top.astype(np.int32)
        l = left.astype(np.int32)
        tl = int(topleft)
        xs = np.arange(8)
        h = int((xs + 1) @ (t[8:16] - np.concatenate(([tl], t[0:7]))[::-1]))
        v = int((xs + 1) @ (l[8:16] - np.concatenate(([tl], l[0:7]))[::-1]))
        a = 16 * (int(l[15]) + int(t[15]))
        b = (5 * h + 32) >> 6
        c = (5 * v + 32) >> 6
        y, x = np.mgrid[0:16, 0:16]
        return np.clip((a + b * (x - 7) + c * (y - 7) + 16) >> 5, 0, 255).astype(np.uint8)
    raise ValueError(f"bad luma mode {mode}")


def predict_chroma8(mode: int, top: np.ndarray | None, left: np.ndarray | None,
                    topleft: int | None) -> np.ndarray:
    """8x8 chroma prediction for one plane."""
    if mode == CHROMA_V:
        if top is None:
            raise ValueError("vertical chroma prediction requires top")
        return np.tile(top.astype(np.uint8), (8, 1))
    if mode == CHROMA_H:
        if left is None:
            raise ValueError("horizontal chroma prediction requires left")
        return np.tile(left.astype(np.uint8)[:, None], (1, 8))
    if mode == CHROMA_DC:
        pred = np.empty((8, 8), np.uint8)
        for bx, by in ((0, 0), (1, 0), (0, 1), (1, 1)):
            t = top[4 * bx:4 * bx + 4].astype(np.int32) if top is not None else None
            l = left[4 * by:4 * by + 4].astype(np.int32) if left is not None else None
            if (bx, by) in ((0, 0), (1, 1)):
                if t is not None and l is not None:
                    dc = (int(t.sum()) + int(l.sum()) + 4) >> 3
                elif l is not None:
                    dc = (int(l.sum()) + 2) >> 2
                elif t is not None:
                    dc = (int(t.sum()) + 2) >> 2
                else:
                    dc = 128
            elif (bx, by) == (1, 0):  # prefers its own top quarter
                if t is not None:
                    dc = (int(t.sum()) + 2) >> 2
                elif l is not None:
                    dc = (int(l.sum()) + 2) >> 2
                else:
                    dc = 128
            else:                     # (0, 1): prefers its own left quarter
                if l is not None:
                    dc = (int(l.sum()) + 2) >> 2
                elif t is not None:
                    dc = (int(t.sum()) + 2) >> 2
                else:
                    dc = 128
            pred[4 * by:4 * by + 4, 4 * bx:4 * bx + 4] = dc
        return pred
    if mode == CHROMA_PLANE:
        if top is None or left is None or topleft is None:
            raise ValueError("plane chroma prediction requires top+left+corner")
        t = top.astype(np.int32)
        l = left.astype(np.int32)
        tl = int(topleft)
        xs = np.arange(4)
        h = int((xs + 1) @ (t[4:8] - np.concatenate(([tl], t[0:3]))[::-1]))
        v = int((xs + 1) @ (l[4:8] - np.concatenate(([tl], l[0:3]))[::-1]))
        a = 16 * (int(l[7]) + int(t[7]))
        b = (34 * h + 32) >> 6
        c = (34 * v + 32) >> 6
        y, x = np.mgrid[0:8, 0:8]
        return np.clip((a + b * (x - 3) + c * (y - 3) + 16) >> 5, 0, 255).astype(np.uint8)
    raise ValueError(f"bad chroma mode {mode}")


def reconstruct_luma16(pred: np.ndarray, dc_levels: np.ndarray,
                       ac_levels: np.ndarray, qp: int) -> np.ndarray:
    """Rebuild a 16x16 luma MB from signaled levels.

    dc_levels: (16,) zig-zag luma DC levels; ac_levels: (16, 15) per-block
    zig-zag AC levels in z-scan block order (all-zero when cbp_luma == 0).
    """
    dc_block = inverse_zigzag(dc_levels.astype(np.int32))     # (4,4) spatial
    dc_recon = luma_dc_dequant(dc_block, qp)                  # (4,4)
    out = np.empty((16, 16), np.int32)
    for bi, (bx, by) in enumerate(LUMA_BLOCK_ORDER):
        seq = np.zeros(16, np.int32)
        seq[1:] = ac_levels[bi]
        z = inverse_zigzag(seq)
        d = dequant_4x4(z, qp)
        d[0, 0] = dc_recon[by, bx]
        r = (inverse_4x4(d) + 32) >> 6
        p = pred[4 * by:4 * by + 4, 4 * bx:4 * bx + 4].astype(np.int32)
        out[4 * by:4 * by + 4, 4 * bx:4 * bx + 4] = p + r
    return np.clip(out, 0, 255).astype(np.uint8)


def reconstruct_chroma8(pred: np.ndarray, dc_levels: np.ndarray,
                        ac_levels: np.ndarray, qpc: int) -> np.ndarray:
    """Rebuild one 8x8 chroma plane of a MB.

    dc_levels: (4,) raster-scan 2x2 DC levels; ac_levels: (4, 15) per-block
    zig-zag AC levels in CHROMA_BLOCK_ORDER.
    """
    dc_recon = chroma_dc_dequant(dc_levels.astype(np.int32).reshape(2, 2), qpc)
    out = np.empty((8, 8), np.int32)
    for bi, (bx, by) in enumerate(CHROMA_BLOCK_ORDER):
        seq = np.zeros(16, np.int32)
        seq[1:] = ac_levels[bi]
        z = inverse_zigzag(seq)
        d = dequant_4x4(z, qpc)
        d[0, 0] = dc_recon[by, bx]
        r = (inverse_4x4(d) + 32) >> 6
        p = pred[4 * by:4 * by + 4, 4 * bx:4 * bx + 4].astype(np.int32)
        out[4 * by:4 * by + 4, 4 * bx:4 * bx + 4] = p + r
    return np.clip(out, 0, 255).astype(np.uint8)
