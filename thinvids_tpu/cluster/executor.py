"""Executor: turns a reserved Job into sharded encode waves + a muxed file.

The data-plane half the coordinator was missing: the reference's worker
task chain `transcode → split → encode×N → stitch`
(/root/reference/worker/tasks.py:810-833, 1354, 1741) collapsed onto a
device mesh — "split" is the GOP plan, "encode×N" is the shard_map wave
fan-out, "stitch" is the ordered concat + MP4 mux. Progress, heartbeats
and completion flow back through the coordinator's token-fenced
callbacks; a stale token halts the run between waves (the reference's
halt checks at every stage, worker/tasks.py:1611-1651).

Wave-level fault handling replaces the reference's part-level retry
(worker/tasks.py:1385-1464): a wave that raises is re-dispatched up to
`part_failure_max_retries` times before the job fails with stage/host
attribution.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable

from ..core.status import Status
from ..ingest.decode import open_video
from ..io.mp4 import mux_mp4
from ..core.types import concat_segments
from ..obs import flight as obs_flight
from ..obs import trace as obs_trace
from .coordinator import Coordinator
from .jobs import Job


class HaltedError(RuntimeError):
    """Run token went stale mid-run (stop/restart/watchdog revocation)."""


def _live_batch_plan(num_frames: int, gop_frames: int,
                     num_devices: int):
    """Fixed GOP grid for one live batch: exactly `gop_frames` per GOP
    (short tail at end of stream), indices local to the batch. The
    default planner's wave balancing would split GOPs differently per
    batch size / mesh width, making live part boundaries
    nondeterministic. (Shared with the SFE encoder's GOP walk —
    parallel/planner.plan_fixed_segments.)"""
    from ..parallel.planner import plan_fixed_segments

    return plan_fixed_segments(num_frames, gop_frames, num_devices)


class _WaveExhausted(RuntimeError):
    """One wave burned its whole retry budget; carries the segments the
    failing range completed so an elastic replan can resume after them."""

    def __init__(self, reason: str, completed: list) -> None:
        super().__init__(reason)
        self.reason = reason
        self.completed = completed


class LocalExecutor:
    """Runs reserved jobs on the local process's device mesh.

    Plugs into :class:`Coordinator` as its launcher: `launch()` spawns a
    worker thread per job (pass ``sync=True`` for deterministic tests).
    """

    def __init__(self, coordinator: Coordinator, output_dir: str,
                 mesh=None, host: str = "local", sync: bool = False,
                 encoder_factory: Callable | None = None) -> None:
        self.coordinator = coordinator
        self.output_dir = output_dir
        self.mesh = mesh
        self.host = host
        self.sync = sync
        #: test seam: (meta, settings, mesh) -> GopShardEncoder-like
        self._encoder_factory = encoder_factory or self._default_encoder
        self._threads: list[threading.Thread] = []
        # flight-recorder artifacts (<job>.trace.json) land next to the
        # output tree this executor writes (obs/flight.py)
        obs_flight.configure(output_dir)

    # -- coordinator launcher interface --------------------------------

    def launch(self, job: Job) -> None:
        if self.sync:
            self.run(job)
            return
        t = threading.Thread(target=self.run, args=(job,), daemon=True,
                             name=f"tvt-exec-{job.id[:8]}")
        self._threads.append(t)
        t.start()

    def join(self, timeout: float | None = None) -> None:
        for t in self._threads:
            t.join(timeout)

    # -- pipeline ------------------------------------------------------

    @staticmethod
    def _default_encoder(meta, settings, mesh):
        """Plan-driven encoder resolution (parallel/dispatch.
        make_shard_encoder): `sfe_bands > 0` selects the split-frame
        band shape (one frame sharded across the mesh as MB-row band
        slices — the single-stream latency path; 0 keeps current
        behavior byte-identical), else GOP waves. The remote backend
        resolves through the SAME seam — its band shape additionally
        spans hosts (cluster/remote.py band shards + halo relay)."""
        from ..parallel.dispatch import make_shard_encoder

        return make_shard_encoder(meta, settings, mesh)

    def run(self, job: Job) -> None:
        token = job.run_token
        # bind the job's trace context to this thread: spans record
        # through the encoder's StageProfile + the wave loop below, and
        # the structured JSON log mode stamps (job_id, trace_id) onto
        # every line emitted while the run owns this thread
        with obs_trace.bind(job.id, obs_trace.TRACE.trace_id(job.id)):
            self._run_traced(job, token)

    def _run_traced(self, job: Job, token: str) -> None:
        co = self.coordinator
        # one-element list: the encode hook advances the stage marker in
        # place so failure attribution survives the subclass seam
        stage = ["probe"]
        source = None
        try:
            settings = co.job_settings(job)
            co.heartbeat_job(job.id, token, stage[0], host=self.host)
            if getattr(job, "job_type", "transcode") == "live":
                # live LL-HLS: the source is still GROWING — tail it
                # GOP-by-GOP and serve viewers during ingest (live/).
                # Always encoded on this process's mesh, even under the
                # remote backend: farming one GOP at a time would put a
                # worker round-trip inside the glass-to-playlist path.
                with self._maybe_trace(settings, job):
                    self._run_live(job, token, settings, stage)
                return
            # streaming ingest: open (header parse / container demux)
            # WITHOUT decoding — frames decode wave-by-wave during the
            # encode, so the clip never materializes in host RAM and
            # time-to-first-wave is one wave's decode
            source = open_video(job.input_path)
            meta, audio = source.meta, source.audio
            if not len(source):
                raise ValueError(f"no frames in {job.input_path}")
            if not co.mark_running(job.id, token):
                raise HaltedError("fenced before start")

            if getattr(job, "job_type", "transcode") == "ladder":
                # ABR ladder: rungs encode from ONE staged wave stream
                # (lower rungs derive on device) and the output is a
                # served HLS directory, not a single MP4 (abr/).
                with self._maybe_trace(settings, job):
                    rungs, rung_segs = self._encode_ladder(
                        job, token, source, settings, meta, stage)
                self._package_ladder(job, token, rungs, rung_segs, meta,
                                     audio, settings, len(source), stage)
                return

            with self._maybe_trace(settings, job):
                segments = self._encode_job(job, token, source, settings,
                                            meta, stage)

            stage[0] = "stitch"
            co.heartbeat_job(job.id, token, stage[0], host=self.host)
            stream = concat_segments(segments)
            base = os.path.splitext(os.path.basename(job.input_path))[0]
            out_path = os.path.join(self.output_dir, base + ".mp4")
            os.makedirs(self.output_dir, exist_ok=True)
            data = mux_mp4(stream, meta, audio=audio)
            tmp = f"{out_path}.{job.id}.tmp"    # job-unique: no clobber
                                                # across same-name jobs
            with open(tmp, "wb") as fp:
                fp.write(data)
            os.replace(tmp, out_path)       # atomic commit (ref: tasks.py:769)
            co.update_progress(job.id, token, combine_progress=100.0)
            co.complete_job(job.id, token, out_path, len(data))
        except HaltedError:
            pass                            # fenced: a newer run owns the job
        except Exception as exc:            # noqa: BLE001 - attribute & fail
            co.fail_job(job.id, token, stage=stage[0], host=self.host,
                        reason=f"{type(exc).__name__}: {exc}")
        finally:
            if source is not None:
                source.close()

    def _encode_job(self, job: Job, token: str, frames, settings, meta,
                    stage: list) -> list:
        """segment + encode stages → ordered EncodedSegments. The seam
        the remote backend overrides (cluster/remote.py dispatches GOP
        shards to worker daemons here); this implementation runs on the
        local process's device mesh. `frames` is a lazy FrameSource
        (len + slicing + iteration; ingest/decode.py) — treat it as a
        sequence, never materialize it wholesale. `stage` is a
        one-element list the hook mutates for failure attribution."""
        co = self.coordinator
        stage[0] = "segment"
        enc = self._encoder_factory(meta, settings, self.mesh)
        self._bind_trace(job, enc)
        plan = enc.plan(len(frames))
        co.update_progress(job.id, token, parts_total=plan.num_gops,
                           segment_progress=100.0)
        co.heartbeat_job(job.id, token, stage[0], host=self.host,
                         note=f"{plan.num_gops} GOPs planned")

        stage[0] = "encode"
        target_kbps = float(settings.get("target_bitrate_kbps", 0.0))
        if str(settings.rc_mode) == "vbr2pass" and target_kbps > 0:
            segments = self._encode_vbr2pass(job, token, enc, frames,
                                             settings, meta, target_kbps)
        else:
            segments = self._encode_with_retry(job, token, enc, frames,
                                               settings)
        self._emit_stage_breakdown(job, enc)
        return segments

    def _encode_ladder(self, job: Job, token: str, frames, settings,
                       meta, stage: list):
        """Ladder encode stage: one LadderShardEncoder fans every wave
        across the rung set on the local mesh (decode + H2D once; lower
        rungs scale on device). Returns (rungs, {rung name → ordered
        EncodedSegments}). The seam the remote backend overrides to
        farm rung×shard work instead (cluster/remote.py)."""
        from ..abr.ladder import plan_ladder, rung_segments
        from ..parallel.dispatch import make_shard_encoder

        co = self.coordinator
        if str(settings.rc_mode) == "vbr2pass":
            # the two-pass QP solver has no multi-rendition form yet;
            # say so instead of silently dropping the bitrate target
            co.activity.emit(
                "encode", "ladder jobs use the octave-model per-rung "
                "QPs; rc_mode=vbr2pass / target_bitrate_kbps ignored",
                job_id=job.id, host=self.host)
        stage[0] = "segment"
        rungs = plan_ladder(meta, settings)
        enc = make_shard_encoder(meta, settings, self.mesh, rungs=rungs)
        self._bind_trace(job, enc)
        plan = enc.plan(len(frames))
        co.update_progress(job.id, token, parts_total=plan.num_gops,
                           segment_progress=100.0)
        co.heartbeat_job(
            job.id, token, stage[0], host=self.host,
            note=f"{plan.num_gops} GOPs x {len(rungs)} rungs")

        stage[0] = "encode"
        # no elastic replan for ladders: a mesh change mid-job would
        # re-plan GOP boundaries and break cross-rung segment alignment
        bundles = self._encode_with_retry(job, token, enc, frames,
                                          settings, allow_replan=False)
        self._emit_stage_breakdown(job, enc)
        return rungs, {r.name: rung_segments(bundles, r.name)
                       for r in rungs}

    def _package_ladder(self, job: Job, token: str, rungs, rung_segs,
                        meta, audio, settings, num_frames: int,
                        stage: list) -> None:
        """Package stage: rungs → fMP4 segments + playlists under
        `<output_dir>/<base>.hls/`, lint-checked, committed with an
        atomic directory rename; the job completes pointing at the
        master playlist (served via /hls/<job>/master.m3u8)."""
        import shutil

        from ..abr import hls

        co = self.coordinator
        stage[0] = "package"
        co.heartbeat_job(job.id, token, stage[0], host=self.host,
                         note=f"{len(rungs)} rungs → HLS")
        # audio passes through bit-exact on EVERY rung: variants must
        # share one codec set or an adaptive down-switch at a segment
        # edge drops the sound track (players handle codec-set changes
        # across variants poorly); the duplicated compressed audio is
        # noise next to any rung's video bytes
        streams = [hls.RungStream(
            name=r.name, width=r.width, height=r.height,
            segments=rung_segs[r.name], audio=audio) for r in rungs]
        base = os.path.splitext(os.path.basename(job.input_path))[0]
        out_dir = os.path.join(self.output_dir, base + ".hls")
        tmp = f"{out_dir}.{job.id}.tmp"     # job-unique staging dir
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            hls.package_ladder(
                tmp, streams, meta.fps_num, meta.fps_den,
                segment_s=float(settings.get("segment_s", 6.0)))
            fps = meta.fps_num / max(1, meta.fps_den)
            hls.lint_ladder(tmp, expected_duration_s=num_frames / fps)
            shutil.rmtree(out_dir, ignore_errors=True)
            os.rename(tmp, out_dir)         # atomic commit
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        total = 0
        for root, _dirs, files in os.walk(out_dir):
            total += sum(os.path.getsize(os.path.join(root, f))
                         for f in files)
        master = os.path.join(out_dir, hls.MASTER_PLAYLIST)
        co.update_progress(job.id, token, combine_progress=100.0)
        co.complete_job(job.id, token, master, total)

    def _run_live(self, job: Job, token: str, settings,
                  stage: list) -> None:
        """Live LL-HLS pipeline: tail the growing source, encode each
        completed GOP through the ladder encoders wave-by-wave, and
        hand every finished GOP bundle to the incremental packager —
        output availability is decoupled from job completion (the
        master playlist is published, and /hls serves it, after the
        FIRST GOP clears all rungs).

        Latency model: at the live edge one GOP encodes at a time
        (glass-to-playlist ≈ GOP duration + one wave's encode+package);
        during backlog/catch-up, up to one full wave of GOPs batches
        per dispatch. End-of-stream is the tail source's stall timeout
        (`live_stall_s`) or `.eos` marker; the packager then finalizes
        with EXT-X-ENDLIST and — when nothing was GC'd out of the DVR
        window — the tree passes the full VOD conformance lint. Waves
        do not retry or replan here: a live edge cannot rewind, so a
        wave failure fails the job with attribution."""
        import shutil

        from ..abr import hls
        from ..abr.ladder import plan_ladder
        from ..ingest.tail import TailFrameSource
        from ..live.packager import LiveLadderPackager

        co = self.coordinator
        stage[0] = "tail"
        stall = float(settings.get("live_stall_s", 10.0))
        tail = TailFrameSource(job.input_path, stall_timeout_s=stall)
        meta = tail.meta                    # header facts; num_frames grows
        if not co.mark_running(job.id, token):
            raise HaltedError("fenced before start")
        gop_n = int(settings.gop_frames)
        rungs = plan_ladder(meta, settings)
        enc, sfe_live = self._live_encoder(meta, settings, rungs)
        self._bind_trace(job, enc)
        base = os.path.splitext(os.path.basename(job.input_path))[0]
        out_dir = os.path.join(self.output_dir, base + ".hls")
        os.makedirs(self.output_dir, exist_ok=True)
        # a restarted live job re-tails from frame 0: the previous
        # attempt's tree is stale output, not resumable state
        shutil.rmtree(out_dir, ignore_errors=True)
        packager = LiveLadderPackager(
            out_dir, rungs, meta.fps_num, meta.fps_den,
            segment_s=float(settings.get("segment_s", 6.0)),
            gop_frames=gop_n,
            dvr_window_s=float(settings.get("dvr_window_s", 0.0)))
        co.heartbeat_job(
            job.id, token, stage[0], host=self.host,
            note=f"tailing x{len(rungs)} rungs (stall {stall:.0f}s)")

        def fenced() -> bool:
            return not co.token_is_current(job.id, token)

        stage[0] = "encode"
        # Prime the jit cache for the live-edge wave shape NOW, while
        # the source is still filling its first GOP: the first part's
        # glass-to-playlist latency must not pay the compile (tens of
        # seconds on a real TPU). One dummy wave, output discarded.
        self._warm_live_shapes(enc, meta, gop_n)
        # QoS deadline: a live batch slower than this budget preempts
        # batch work on the cluster until the edge recovers
        # (cluster/qos.py). 0 = auto: 2x the stream's segment duration.
        part_budget = float(settings.get("live_part_budget_s", 0.0)) \
            or 2.0 * float(settings.get("segment_s", 6.0))
        wave_cap = self._live_backlog_cap(job, settings, enc)
        frames_done = gops_done = 0
        published = False
        while True:
            avail = tail.wait_frames(frames_done + gop_n,
                                     stop_check=fenced)
            batch_t0 = time.monotonic()
            if fenced():
                raise HaltedError("stale run token")
            if avail <= frames_done and tail.ended:
                break
            if tail.ended:
                # drain wave-by-wave (the final partial GOP rides the
                # last batch) — never one giant batch, a fast writer
                # can leave an arbitrarily deep backlog at EOS
                count = min(avail - frames_done, wave_cap * gop_n)
            else:
                whole = (avail - frames_done) // gop_n
                # at the live edge whole==1 (lowest latency); during
                # catch-up batch up to the backlog cap per dispatch
                # (one local wave — or the whole farm's width when the
                # remote backend fans catch-up GOPs out)
                count = min(whole, wave_cap) * gop_n
            bundles = self._live_encode_batch(
                job, token, settings, enc, rungs, tail, frames_done,
                gops_done, count, gop_n, sfe_live)
            for bundle in bundles:
                packager.add_gop(bundle)
            if not published:
                # the served tree now exists: announce it while the
                # job keeps RUNNING — viewers join during ingest
                co.publish_output(job.id, token, packager.master_path)
                published = True
            gops_done += len(bundles)
            frames_done += count
            # deadline report: wall-clock from the batch's frames being
            # available to its parts being fetchable — over budget,
            # the coordinator preempts batch shards (cluster/qos.py)
            co.note_live_part(job.id, token,
                              time.monotonic() - batch_t0, part_budget)
            co.update_progress(job.id, token, parts_total=gops_done,
                               parts_done=gops_done,
                               segment_progress=100.0)
            co.heartbeat_job(
                job.id, token, stage[0], host=self.host,
                note=f"live edge: {gops_done} GOPs, "
                     f"{packager.segments_announced} segments, "
                     f"{packager.segments_gced} GC'd")
        if gops_done == 0:
            raise ValueError(
                f"live source {job.input_path} ended with no frames")

        stage[0] = "finalize"
        co.heartbeat_job(job.id, token, stage[0], host=self.host,
                         note="end of stream; writing ENDLIST")
        packager.close()
        fps = meta.fps_num / max(1, meta.fps_den)
        if packager.segments_gced == 0:
            # nothing left the DVR window: the closed tree is a full
            # VOD and must pass the batch conformance gate unchanged
            hls.lint_ladder(out_dir,
                            expected_duration_s=frames_done / fps)
        else:
            for r in rungs:
                hls.lint_live_media_playlist(os.path.join(
                    out_dir, r.name, hls.MEDIA_PLAYLIST))
        self._emit_stage_breakdown(job, enc)
        co.update_progress(job.id, token, encode_progress=100.0,
                           combine_progress=100.0)
        co.complete_job(job.id, token, packager.master_path,
                        packager.total_bytes())

    def _live_encoder(self, meta, settings, rungs):
        """Live-edge encoder selection (plan-driven, like every other
        path): the ladder stack by default; a SINGLE-rung stream with
        `sfe_bands > 0` runs the split-frame encoder at the live edge
        instead — every frame sharded across the mesh as band slices,
        so glass-to-playlist latency rides the per-frame SFE pipeline
        rather than whole-GOP waves. Returns (encoder, sfe_mode)."""
        from ..parallel.dispatch import make_shard_encoder

        sfe_bands = int(settings.get("sfe_bands", 0) or 0)
        if sfe_bands > 0 and len(rungs) == 1:
            return make_shard_encoder(meta, settings, self.mesh,
                                      shape="band"), True
        return make_shard_encoder(meta, settings, self.mesh,
                                  rungs=rungs), False

    def _live_backlog_cap(self, job, settings, enc) -> int:
        """Whole GOPs one catch-up dispatch may batch: one local wave.
        The remote backend widens this to the farm (its override fans
        the backlog across workers) — but only when the fan-out will
        actually engage, so a disabled knob keeps the pre-farm local
        batch bound."""
        return enc.num_devices * enc.gops_per_wave

    def _live_encode_batch(self, job, token, settings, enc, rungs,
                           tail, frames_done: int, gops_done: int,
                           count: int, gop_n: int, sfe_live: bool):
        """Encode one live batch (the seam the remote backend overrides
        to fan catch-up GOPs across the farm). GOP indices / frame
        ranges continue the global stream (same offset contract the
        elastic replan uses), and the batch's GOP boundaries are
        pinned EXPLICITLY: the local planner balances GOP lengths to
        the mesh width, which would make part boundaries depend on
        arrival timing and device count — a live stream's GOP grid
        must be a pure function of the frame index (gop_frames-sized,
        like the remote backend's shard plan_override contract)."""
        enc.gop_index_offset = gops_done
        enc.frame_offset = frames_done
        enc.plan_override = _live_batch_plan(count, gop_n,
                                             enc.num_devices)
        # lazy window, not a materialized list: the staging thread
        # decodes the batch wave-by-wave (bounded residency, same
        # contract as batch ingest)
        out = enc.encode(tail[frames_done:frames_done + count])
        if not sfe_live:
            return out
        # SFE live edge: plain EncodedSegments wrap into single-rung
        # bundles so the incremental packager consumes them unchanged
        from ..abr.ladder import LadderGopBundle

        return [LadderGopBundle(gop=s.gop,
                                renditions={rungs[0].name: s})
                for s in out]

    @staticmethod
    def _warm_live_shapes(enc, meta, gop_n: int) -> None:
        """Compile the live-edge wave program (one gop_n-frame GOP,
        padded to the mesh width like every live batch) on synthetic
        frames before real ones arrive — overlap jit compile with the
        source's first-GOP fill instead of serializing it into the
        first part's latency."""
        import numpy as np

        from ..core.types import Frame

        h, w = meta.height, meta.width
        dummy = [Frame(y=np.zeros((h, w), np.uint8),
                       u=np.full((h // 2, w // 2), 128, np.uint8),
                       v=np.full((h // 2, w // 2), 128, np.uint8))
                 for _ in range(gop_n)]
        enc.plan_override = _live_batch_plan(gop_n, gop_n,
                                             enc.num_devices)
        try:
            enc.encode(dummy)
        except Exception:       # noqa: BLE001 - warm is best-effort;
            pass                # a real defect fails the REAL first
                                # wave with proper attribution

    def _bind_trace(self, job: Job, enc) -> None:
        """Bind the job's span recorder to the encoder's stage profile:
        every timed stage (decode/stage/dispatch/device_wait/fetch/
        pack/concat, SFE per-frame) then records a span into the job's
        distributed trace. Inert when the job was sampled out
        (trace_sample) or the encoder is a test double without a
        profile."""
        stages = getattr(enc, "stages", None)
        set_tracer = getattr(stages, "set_tracer", None)
        if set_tracer is not None:
            set_tracer(obs_trace.TRACE.recorder(job.id, host=self.host))

    def _emit_stage_breakdown(self, job: Job, enc) -> None:
        """Record the encoder's host-stage wall-clock breakdown (wave
        dispatch / device wait / D2H fetch / sparse unpack / unflatten /
        CAVLC pack / concat) in the job's activity feed — the per-job
        counterpart of /metrics_snapshot's live aggregate."""
        stages = getattr(enc, "stages", None)
        if stages is None:
            return
        import json

        self.coordinator.activity.emit(
            "encode", "stage_ms " + json.dumps(stages.snapshot()),
            job_id=job.id, host=self.host)

    @staticmethod
    def _maybe_trace(settings, job: Job):
        """jax.profiler trace of the encode stage when `profile_dir` is
        set (SURVEY §5.1: the reference had activity timers only; here
        per-kernel device timelines land beside the job's events)."""
        import contextlib

        profile_dir = str(settings.get("profile_dir", "") or "")
        if not profile_dir:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(
            os.path.join(profile_dir, f"job-{job.id[:8]}"))

    def _encode_vbr2pass(self, job: Job, token: str, enc, frames,
                         settings, meta, target_kbps: float) -> list:
        """Two-pass VBR via rc.encode_vbr2pass's single solve/refine
        loop, with every pass riding this executor's retry/halt/progress
        wrapper and heartbeating its pass number."""
        from ..parallel import rc

        co = self.coordinator

        def on_pass(pass_no, gop_qps):
            note = ("vbr pass 1 (analysis)" if gop_qps is None else
                    f"vbr pass {pass_no} (qp {gop_qps.min()}"
                    f"-{gop_qps.max()})")
            co.heartbeat_job(job.id, token, "encode", host=self.host,
                             note=note)

        segments, _stats = rc.encode_vbr2pass(
            frames, meta, target_kbps, base_qp=int(settings.qp), enc=enc,
            encode_fn=lambda e: self._encode_with_retry(
                job, token, e, frames, settings, allow_replan=False),
            on_pass=on_pass,
            aq_strength=float(settings.get("aq_strength", 0.0) or 0.0))
        return segments

    def _encode_with_retry(self, job: Job, token: str, enc, frames,
                           settings, allow_replan: bool = True) -> list:
        """Wave loop with per-wave retry, halt checks, and elastic
        replan: when a wave exhausts its retry budget on a multi-device
        mesh, the remaining frames are re-planned on a SHRUNKEN mesh and
        encoding continues — the TPU analog of the reference's elastic
        worker set (parts re-placed on healthy nodes,
        worker/tasks.py:1845-2029; SURVEY §2.9 "Elastic DP"). A
        single-device failure has nowhere left to shrink and fails the
        job with attribution.

        `allow_replan=False` (the vbr2pass passes) fails instead of
        replanning: a mesh change mid-pass would change the GOP count
        under the QP solver and orphan the per-GOP QP map.
        """
        co = self.coordinator
        total_gops = enc.plan(len(frames)).num_gops
        segments: list = []
        start_frame = 0
        shrink_attempt = 0
        while True:
            try:
                segments.extend(self._encode_range(
                    job, token, enc, frames, start_frame, settings,
                    total_gops, len(segments)))
                segments.sort(key=lambda s: s.gop.index)
                return segments
            except _WaveExhausted as exc:
                segments.extend(exc.completed)
                shrink_attempt += 1
                shrunk = (self._shrink_encoder(enc, settings,
                                               shrink_attempt)
                          if allow_replan else None)
                if shrunk is None:
                    raise RuntimeError(exc.reason) from exc
                # completed waves are a contiguous frame prefix (waves
                # collect in order); resume after it on the new mesh
                start_frame = max(
                    (s.gop.end_frame for s in segments), default=0)
                # the suffix re-plans with a different device count, so
                # the GOP total changes — keep progress honest
                total_gops = len(segments) + shrunk.plan(
                    len(frames) - start_frame).num_gops
                co.update_progress(job.id, token, parts_total=total_gops)
                co.activity.emit(
                    "encode", f"wave retries exhausted; replanning "
                    f"frames {start_frame}+ on {shrunk.num_devices} "
                    f"devices (was {enc.num_devices})",
                    job_id=job.id, host=self.host)
                enc = shrunk

    def _qos_pause(self, job: Job, token: str, settings) -> None:
        """Hold a BATCH-class job's wave loop while the QoS controller
        has batch work preempted for a struggling live edge
        (cluster/qos.py): in-flight waves drain, no new wave
        dispatches, heartbeats keep the watchdog off. Ladder and live
        jobs never pause; re-raises HaltedError if fenced mid-pause."""
        from .qos import BATCH_RANK, job_rank

        co = self.coordinator
        qos = getattr(co, "qos", None)
        if qos is None or qos.batch_allowed():
            return
        override = str(settings.get("job_priority", "auto") or "auto")
        if job_rank(getattr(job, "job_type", "transcode"),
                    override) < BATCH_RANK:
            return
        co.activity.emit("qos", "batch waves paused: live QoS "
                         "preemption", job_id=job.id, host=self.host)
        while not qos.wait_batch_allowed(0.1):
            if not co.token_is_current(job.id, token):
                raise HaltedError("stale run token")
            co.heartbeat_job(job.id, token, "encode", host=self.host,
                             note="paused: live QoS preemption")

    def _shrink_encoder(self, enc, settings, attempt: int):
        """Encoder over a shrunken copy of enc's mesh, or None when it
        cannot shrink further (or the encoder exposes no mesh).

        A Python-level wave failure carries no device attribution, so
        the shrink is blind — it drops devices from the tail, doubling
        the count each consecutive attempt (1, 2, 4, ...) so a bad
        device at a low index is excluded in O(log n) rounds rather
        than n full retry budgets."""
        mesh = getattr(enc, "mesh", None)
        meta = getattr(enc, "meta", None)
        if mesh is None or meta is None:
            return None
        devices = list(mesh.devices.flat)
        if len(devices) <= 1:
            return None
        drop = min(len(devices) - 1, 2 ** (attempt - 1))
        import numpy as np
        from jax.sharding import Mesh

        return self._encoder_factory(
            meta, settings, Mesh(np.array(devices[:-drop]), ("gop",)))

    def _encode_range(self, job: Job, token: str, enc, frames,
                      start_frame: int, settings, total_gops: int,
                      done0: int) -> list:
        """Depth-2 pipelined wave loop over frames[start_frame:].

        The decode → stack → H2D staging chain runs on a background
        staging thread (`decode_ahead` waves ahead of the dispatch
        window — parallel/dispatch.background_stage), so ingest
        overlaps device compute instead of serializing ahead of it.
        Staging stays bounded, not free: input residency is now the 2
        in-flight waves PLUS up to `decode_ahead` staged-but-undispatched
        waves (+1 blocked in the queue put) of HBM-resident YUV arrays —
        size `decode_ahead` against the device's HBM headroom, not just
        source latency. A retried wave re-dispatches from its retained
        staged tuple.
        Raises _WaveExhausted (carrying the range's completed segments)
        when one wave fails `part_failure_max_retries` times.
        """
        from ..parallel.dispatch import GopShardEncoder, background_stage

        co = self.coordinator
        max_retries = int(settings.part_failure_max_retries)
        if start_frame:
            # GOP indices / frame ranges restart at 0 for the subrange;
            # offset emitted segments so ordering + idr_pic_id stay
            # globally consistent with already-completed ones
            enc.gop_index_offset = done0
            enc.frame_offset = start_frame
        # the encoder already resolved the `decode_ahead` setting in
        # its constructor (like pack_workers/pipeline_window), so honor
        # its knob — incl. explicit constructor overrides; the class
        # default only covers test doubles that lack the attribute
        decode_ahead = int(getattr(enc, "decode_ahead", 0) or 0) \
            or GopShardEncoder.DECODE_AHEAD
        feed = background_stage(
            enc.stage_waves(frames[start_frame:] if start_frame
                            else frames),
            decode_ahead)
        staged_iter = enumerate(feed)
        segments: list = []
        done = done0
        pending: deque = deque()        # (idx, staged, handle)
        attempts: dict[int, int] = {}
        # per-wave spans in the job's distributed trace (inert when
        # the job was sampled out — trace_sample)
        rec = obs_trace.TRACE.recorder(job.id, host=self.host)

        def halt_check() -> None:
            if not co.token_is_current(job.id, token):
                raise HaltedError("stale run token")

        def dispatch_next() -> None:
            try:
                i, staged = next(staged_iter)
            except StopIteration:
                return
            with rec.span("wave_dispatch", wave=i):
                pending.append((i, staged, enc.dispatch_wave(staged)))

        try:
            dispatch_next()
            while pending:
                halt_check()
                self._qos_pause(job, token, settings)
                if len(pending) < 2:
                    dispatch_next()     # overlap: depth-2 window, no more
                i, staged, handle = pending.popleft()
                try:
                    with rec.span("wave_collect", wave=i):
                        segs = enc.collect_wave(handle)
                except HaltedError:
                    raise
                except Exception as exc:  # noqa: BLE001 - wave retry budget
                    n = attempts.get(i, 0) + 1
                    attempts[i] = n
                    if n > max_retries:
                        raise _WaveExhausted(
                            f"wave {i} failed after {n - 1} retries: "
                            f"{type(exc).__name__}: {exc}", segments) \
                            from exc
                    co.activity.emit(
                        "encode", f"wave {i} attempt {n} failed, "
                        f"retrying: {exc}", job_id=job.id, host=self.host)
                    # staged[0] is the wave's GOP list (GopShardEncoder)
                    # or a single GopSpec (SfeShardEncoder: one GOP per
                    # wave, frames sharded as bands within it)
                    wave_gops = (len(staged[0])
                                 if hasattr(staged[0], "__len__") else 1)
                    retried = co.store.get(job.id).parts_retried \
                        + wave_gops
                    co.update_progress(job.id, token, parts_retried=retried)
                    halt_check()
                    pending.appendleft((i, staged,
                                        enc.dispatch_wave(staged)))
                    continue
                segments.extend(segs)
                done += len(segs)
                co.update_progress(
                    job.id, token, parts_done=done,
                    encode_progress=100.0 * done / max(1, total_gops))
                co.heartbeat_job(job.id, token, "encode", host=self.host,
                                 note=f"{done}/{total_gops} GOPs")
            return segments
        finally:
            feed.close()                # stop the staging thread
                                        # (halt / replan / exhaustion)
