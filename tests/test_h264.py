"""H.264 codec tests: transforms, CAVLC, headers, encoder↔decoder, oracle.

Conformance strategy (SURVEY.md §4): golden/structural unit tests per
stage, an in-repo independent decoder cross-check, and a libavcodec
external-oracle bit-exactness test of encoder reconstruction.
"""

import numpy as np
import pytest

from thinvids_tpu.codecs.h264 import cavlc, tables
from thinvids_tpu.codecs.h264.decoder import decode_annexb
from thinvids_tpu.codecs.h264.encoder import (
    H264Encoder,
    encode_frame_arrays,
    encode_frames,
)
from thinvids_tpu.codecs.h264.headers import PPS, SPS
from thinvids_tpu.codecs.h264.transform import (
    MF_TABLE,
    V_TABLE,
    chroma_qp,
    dequant_4x4,
    forward_4x4,
    inverse_4x4,
    inverse_zigzag,
    quant_4x4,
    zigzag,
)
from thinvids_tpu.core.types import Frame, VideoMeta
from thinvids_tpu.io.bits import BitReader, BitWriter
from thinvids_tpu.tools import oracle


def synthetic_frame(w, h, seed=7, flat=False):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    if flat:
        y = np.full((h, w), 128, np.uint8)
    else:
        y = np.clip(((xx * 2 + yy) % 256).astype(int)
                    + rng.integers(-8, 8, (h, w)), 0, 255).astype(np.uint8)
    u = np.clip(128 + (xx[::2, ::2] // 2) - 30
                + rng.integers(-5, 5, (h // 2, w // 2)), 0, 255).astype(np.uint8)
    v = np.clip(128 - (yy[::2, ::2] // 2)
                + rng.integers(-5, 5, (h // 2, w // 2)), 0, 255).astype(np.uint8)
    return Frame(y, u, v)


def psnr(a, b):
    mse = np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    return 10 * np.log10(255**2 / max(mse, 1e-12))


class TestTransform:
    def test_qp0_near_lossless(self):
        # The integer transform pair is only an identity THROUGH the
        # quant/dequant scaling matrices; at qp=0 (finest step) the full
        # loop must reconstruct residuals to within +-1.
        rng = np.random.default_rng(0)
        x = rng.integers(-255, 256, (32, 4, 4)).astype(np.int32)
        w = forward_4x4(x)
        r = (inverse_4x4(dequant_4x4(quant_4x4(w, 0), 0)) + 32) >> 6
        assert np.abs(r - x).max() <= 1

    def test_quant_dequant_monotone(self):
        rng = np.random.default_rng(1)
        x = rng.integers(-200, 200, (16, 4, 4)).astype(np.int32)
        w = forward_4x4(x)
        errs = []
        for qp in (0, 10, 20, 30, 40, 50):
            z = quant_4x4(w, qp)
            d = dequant_4x4(z, qp)
            r = (inverse_4x4(d) + 32) >> 6
            errs.append(np.abs(r - x).mean())
        assert errs == sorted(errs)  # coarser qp → larger error

    def test_zigzag_roundtrip(self):
        x = np.arange(16, dtype=np.int32).reshape(4, 4)
        assert np.array_equal(inverse_zigzag(zigzag(x)), x)
        # spec order: second element is (0,1), third is (1,0)
        assert zigzag(x)[1] == x[0, 1]
        assert zigzag(x)[2] == x[1, 0]

    def test_table_classes(self):
        # position-class values from the spec: (0,0)=class0, (1,1) largest V
        assert MF_TABLE[0][0, 0] == 13107
        assert V_TABLE[0][0, 0] == 10
        assert V_TABLE[0][1, 1] == 16
        assert V_TABLE[0][0, 1] == 13

    def test_chroma_qp_mapping(self):
        assert chroma_qp(0) == 0
        assert chroma_qp(29) == 29
        assert chroma_qp(30) == 29
        assert chroma_qp(51) == 39


class TestCavlcTables:
    @pytest.mark.parametrize("ctx", range(4))
    def test_coeff_token_prefix_free(self, ctx):
        codes = list(tables.COEFF_TOKEN[ctx].values())
        assert tables.check_prefix_free(codes) == []

    def test_chroma_dc_complete(self):
        codes = list(tables.CHROMA_DC_COEFF_TOKEN.values())
        assert tables.check_prefix_free(codes) == []
        assert tables.kraft_sum(codes) == 1.0

    def test_total_zeros_complete(self):
        for tc, codes in tables.TOTAL_ZEROS_4x4.items():
            assert tables.check_prefix_free(codes) == [], tc
            expected = 1.0 if tc != 1 else 1.0 - 2.0**-9
            assert abs(tables.kraft_sum(codes) - expected) < 1e-12, tc
        for tc, codes in tables.TOTAL_ZEROS_CHROMA_DC.items():
            assert tables.kraft_sum(codes) == 1.0

    def test_run_before_complete(self):
        for zl, codes in tables.RUN_BEFORE.items():
            assert tables.check_prefix_free(codes) == [], zl


class TestCavlcRoundtrip:
    @pytest.mark.parametrize("seed", range(3))
    def test_fuzz(self, seed):
        rng = np.random.default_rng(seed)
        for _ in range(2000):
            max_coeff = int(rng.choice([16, 15, 4]))
            nc = -1 if max_coeff == 4 else int(rng.choice([0, 1, 2, 3, 5, 8, 20]))
            coeffs = [0] * max_coeff
            density = rng.uniform(0, 1)
            for i in range(max_coeff):
                if rng.uniform() < density:
                    coeffs[i] = int(rng.choice([1, 1, 2, 3, 5, 9, 200])) * \
                        (1 if rng.uniform() < 0.5 else -1)
            bw = BitWriter()
            cavlc.encode_residual(bw, coeffs, nc)
            bw.byte_align()
            out = cavlc.decode_residual(BitReader(bw.getvalue()), nc, max_coeff)
            assert out == coeffs


class TestHeaders:
    def test_sps_roundtrip(self):
        sps = SPS(width=1920, height=1080, fps_num=30000, fps_den=1001)
        parsed = SPS.parse_rbsp(sps.to_rbsp())
        assert parsed.width == 1920 and parsed.height == 1080
        assert parsed.fps_num == 30000 and parsed.fps_den == 1001

    def test_pps_roundtrip(self):
        pps = PPS(init_qp=33)
        parsed = PPS.parse_rbsp(pps.to_rbsp())
        assert parsed.init_qp == 33
        assert parsed.deblocking_control_present


class TestEncoderDecoder:
    @pytest.mark.parametrize("qp", [10, 27, 40])
    def test_own_decoder_matches_recon(self, qp):
        frame = synthetic_frame(64, 48)
        meta = VideoMeta(width=64, height=48)
        stream = H264Encoder(meta, qp=qp).encode_frame(frame)
        padded = frame.padded(16)
        _, (ry, ru, rv) = encode_frame_arrays(padded.y, padded.u, padded.v, qp)
        dec = decode_annexb(stream)
        assert np.array_equal(dec.frames[0].y, ry[:48, :64])
        assert np.array_equal(dec.frames[0].u, ru[:24, :32])
        assert np.array_equal(dec.frames[0].v, rv[:24, :32])

    def test_cropped_dimensions(self):
        frame = synthetic_frame(36, 20)
        meta = VideoMeta(width=36, height=20)
        stream = H264Encoder(meta, qp=27).encode_frame(frame)
        dec = decode_annexb(stream)
        assert dec.frames[0].y.shape == (20, 36)
        assert dec.meta.width == 36 and dec.meta.height == 20

    def test_multi_frame_stream(self):
        meta = VideoMeta(width=32, height=32)
        frames = [synthetic_frame(32, 32, seed=s) for s in range(3)]
        stream = encode_frames(frames, meta, qp=30)
        dec = decode_annexb(stream)
        assert len(dec.frames) == 3

    def test_quality_improves_with_lower_qp(self):
        frame = synthetic_frame(64, 48)
        meta = VideoMeta(width=64, height=48)
        vals = []
        for qp in (40, 27, 10):
            stream = H264Encoder(meta, qp=qp).encode_frame(frame)
            dec = decode_annexb(stream)
            vals.append(psnr(dec.frames[0].y, frame.y))
        assert vals == sorted(vals)
        assert vals[-1] > 45  # qp=10 should be high fidelity


@pytest.mark.skipif(not oracle.oracle_available(), reason="libavcodec missing")
class TestConformanceOracle:
    @pytest.mark.parametrize("qp", [4, 10, 20, 27, 34, 40, 48])
    def test_bit_exact_vs_libavcodec(self, qp):
        frame = synthetic_frame(64, 48)
        meta = VideoMeta(width=64, height=48)
        stream = H264Encoder(meta, qp=qp).encode_frame(frame)
        padded = frame.padded(16)
        _, (ry, ru, rv) = encode_frame_arrays(padded.y, padded.u, padded.v, qp)
        oy, ou, ov = oracle.decode_h264(stream)[0]
        assert np.array_equal(oy, ry[:48, :64])
        assert np.array_equal(ou, ru[:24, :32])
        assert np.array_equal(ov, rv[:24, :32])

    def test_multi_frame_and_crop(self):
        meta = VideoMeta(width=36, height=20)
        frames = [synthetic_frame(36, 20, seed=s) for s in range(4)]
        stream = encode_frames(frames, meta, qp=24)
        decoded = oracle.decode_h264(stream)
        assert len(decoded) == 4
        assert decoded[0][0].shape == (20, 36)
        # every frame individually bit-exact vs own decoder
        own = decode_annexb(stream)
        for (oy, ou, ov), f in zip(decoded, own.frames):
            assert np.array_equal(oy, f.y)
            assert np.array_equal(ou, f.u)
            assert np.array_equal(ov, f.v)

    def test_flat_frame_minimal_stream(self):
        frame = synthetic_frame(32, 32, flat=True)
        meta = VideoMeta(width=32, height=32)
        stream = H264Encoder(meta, qp=30).encode_frame(frame)
        (oy, ou, ov) = oracle.decode_h264(stream)[0]
        assert np.array_equal(oy, np.full((32, 32), 128))


class TestGuards:
    def test_odd_dimensions_rejected(self):
        with pytest.raises(ValueError, match="odd dimensions"):
            SPS(width=33, height=48).to_rbsp()
        with pytest.raises(ValueError, match="odd dimensions"):
            SPS(width=64, height=47).to_rbsp()

    def test_non_420_input_rejected(self):
        meta = VideoMeta(width=32, height=32)
        enc = H264Encoder(meta, qp=27)
        f422 = Frame(
            y=np.zeros((32, 32), np.uint8),
            u=np.zeros((32, 16), np.uint8),   # full-height chroma: 4:2:2
            v=np.zeros((32, 16), np.uint8),
        )
        with pytest.raises(ValueError, match="4:2:0"):
            enc.encode_frame(f422)

    def test_malformed_chroma_plane_rejected(self):
        f = Frame(
            y=np.zeros((64, 64), np.uint8),
            u=np.zeros((16, 16), np.uint8),   # neither 32 nor 64
            v=np.zeros((16, 16), np.uint8),
        )
        with pytest.raises(ValueError, match="chroma"):
            f.padded(16)

    def test_native_escape_overflow_matches_python(self):
        # A level too large for the baseline CAVLC 12-bit escape must
        # raise in BOTH packers (the native path previously emitted a
        # corrupt stream silently).
        from thinvids_tpu import native
        from thinvids_tpu.codecs.h264.encoder import FrameLevels, pack_slice

        if not native.available():
            pytest.skip("no compiler")
        nmb = 1
        levels = FrameLevels(
            luma_mode=np.zeros(nmb, np.int32),
            chroma_mode=np.zeros(nmb, np.int32),
            luma_dc=np.zeros((nmb, 16), np.int32),
            luma_ac=np.zeros((nmb, 16, 15), np.int32),
            chroma_dc=np.zeros((nmb, 2, 4), np.int32),
            chroma_ac=np.zeros((nmb, 2, 4, 15), np.int32),
        )
        levels.luma_ac[0, 0, 0] = 3000   # level_code far beyond 12-bit escape
        sps = SPS(width=16, height=16)
        pps = PPS(init_qp=27)
        with pytest.raises(ValueError, match="too large"):
            pack_slice(levels, 1, 1, sps, pps, 27, native=True)
        with pytest.raises(ValueError, match="too large"):
            pack_slice(levels, 1, 1, sps, pps, 27, native=False)

    def test_native_int16_islice_matches_int32_and_python(self):
        # The int16 entry (cavlc_pack_islice16, fed by the transfer
        # layout's zero-copy views) must emit the exact bits of the
        # int32 entry and of the pure-Python packer.
        from thinvids_tpu import native
        from thinvids_tpu.codecs.h264.encoder import FrameLevels, pack_slice

        if not native.available():
            pytest.skip("no compiler")
        rng = np.random.default_rng(3)
        nmb = 12
        arrs = {
            "luma_dc": rng.integers(-200, 201, (nmb, 16)),
            "luma_ac": (rng.integers(-8, 9, (nmb, 16, 15))
                        * (rng.random((nmb, 16, 15)) < 0.2)),
            "chroma_dc": rng.integers(-150, 151, (nmb, 2, 4)),
            "chroma_ac": (rng.integers(-5, 6, (nmb, 2, 4, 15))
                          * (rng.random((nmb, 2, 4, 15)) < 0.15)),
        }

        def levels(dtype):
            return FrameLevels(
                luma_mode=np.zeros(nmb, np.int32),
                chroma_mode=np.zeros(nmb, np.int32),
                **{k: v.astype(dtype) for k, v in arrs.items()})

        sps = SPS(width=64, height=48)
        pps = PPS(init_qp=27)
        a32 = pack_slice(levels(np.int32), 4, 3, sps, pps, 27, native=True)
        a16 = pack_slice(levels(np.int16), 4, 3, sps, pps, 27, native=True)
        py = pack_slice(levels(np.int32), 4, 3, sps, pps, 27, native=False)
        assert a16 == a32 == py
        # escape overflow propagates from the int16 entry too (the
        # largest int16 level exceeds the 12-bit escape budget)
        bad = levels(np.int16)
        bad.luma_ac[0, 0, 0] = 3000
        with pytest.raises(ValueError, match="too large"):
            pack_slice(bad, 4, 3, sps, pps, 27, native=True)
