"""Real transcoding: MP4 demux → libavcodec decode → re-encode, with
audio passthrough. The reference's core competency — transcoding
compressed sources, not just raw ingest
(/root/reference/worker/tasks.py:1354-1737) — exercised natively.
"""

import os

import numpy as np
import pytest

from thinvids_tpu.core.types import Frame, VideoMeta
from thinvids_tpu.ingest.decode import DecodeError, read_video
from thinvids_tpu.ingest.probe import probe_video
from thinvids_tpu.io.mp4 import Mp4Track, demux_mp4, mux_mp4, read_mp4
from thinvids_tpu.parallel.dispatch import encode_clip_sharded
from thinvids_tpu.tools import oracle


def _clip(n=8, w=64, h=48):
    yy, xx = np.mgrid[0:h, 0:w]
    return [Frame(((xx * 2 + 3 * i) % 256).astype(np.uint8),
                  np.full((h // 2, w // 2), 100, np.uint8),
                  np.full((h // 2, w // 2), 150, np.uint8))
            for i in range(n)], VideoMeta(width=w, height=h, fps_num=30,
                                          fps_den=1, num_frames=n)


def _fake_audio(n_samples=6):
    # a structurally valid mp4a sample entry (we never decode it)
    entry = (b"\x00\x00\x00\x24mp4a" + b"\x00" * 6 + b"\x00\x01"
             + b"\x00" * 8 + b"\x00\x02\x00\x10" + b"\x00" * 4
             + b"\xbb\x80\x00\x00")
    return Mp4Track(handler="soun", stsd_entry=entry, timescale=48000,
                    stts=[(n_samples, 1024)],
                    samples=[bytes([40 + i]) * 32 for i in range(n_samples)])


class TestDemux:
    def test_roundtrip_own_output(self):
        frames, meta = _clip()
        stream = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        m = demux_mp4(mux_mp4(stream, meta))
        assert (m.width, m.height) == (meta.width, meta.height)
        assert m.num_frames == len(frames)
        assert m.fps == (90000, 3000)           # 30 fps
        assert m.keyflags[0] is True
        # Slice NALs are bit-exact vs the original stream (SPS/PPS are
        # hoisted into avcC once; the source repeats them per GOP head)
        from thinvids_tpu.io.mp4 import split_annexb

        slices = lambda s: [n for n in split_annexb(s)
                            if n[0] & 0x1F in (1, 5)]
        assert slices(m.annexb) == slices(stream)

    def test_audio_track_roundtrip(self):
        frames, meta = _clip()
        stream = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        audio = _fake_audio()
        m = demux_mp4(mux_mp4(stream, meta, audio=audio))
        assert m.audio is not None
        assert m.audio.samples == audio.samples
        assert m.audio.stts == audio.stts
        assert m.audio.timescale == audio.timescale
        assert m.audio.stsd_entry == audio.stsd_entry

    def test_non_avc_video_rejected(self):
        with pytest.raises(ValueError):
            demux_mp4(b"\x00\x00\x00\x08free")

    def test_multi_chunk_and_co64_layout(self):
        """Real-world files spread samples over many chunks and use
        64-bit co64 offsets; the sample walk must reassemble them."""
        import struct

        frames, meta = _clip()
        stream = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        base = mux_mp4(stream, meta)
        ref = demux_mp4(base)

        # rewrite the single-chunk layout as per-sample chunks + co64
        samples = ref.video.samples
        mdat_payload = b"".join(samples)
        # offsets of each sample within a NEW mdat placed after moov
        def rebuild(moov: bytes) -> bytes:
            ftyp = base[:base.find(b"moov") - 4]
            mdat = struct.pack(">I", 8 + len(mdat_payload)) + b"mdat" \
                + mdat_payload
            return ftyp + moov + mdat

        # locate the original stbl pieces and surgically replace
        # stsc (1 sample/chunk) + stco -> co64 with per-sample offsets
        i = base.find(b"stsc") - 4
        size = struct.unpack_from(">I", base, i)[0]
        old_stsc = base[i:i + size]
        new_stsc = struct.pack(">I", 8 + 4 + 4 + 12) + b"stsc" \
            + struct.pack(">II", 0, 1) + struct.pack(">III", 1, 1, 1)
        j = base.find(b"stco") - 4
        size_co = struct.unpack_from(">I", base, j)[0]
        old_stco = base[j:j + size_co]

        # first pass with dummy offsets to learn the layout size
        def co64_box(offsets):
            return struct.pack(">I", 8 + 8 + 8 * len(offsets)) + b"co64" \
                + struct.pack(">II", 0, len(offsets)) \
                + b"".join(struct.pack(">Q", o) for o in offsets)

        moov_start = base.find(b"moov") - 4
        moov_size = struct.unpack_from(">I", base, moov_start)[0]
        moov = base[moov_start:moov_start + moov_size]

        def patch(moov, offsets):
            m = moov.replace(old_stsc, new_stsc).replace(
                old_stco, co64_box(offsets))
            # fix enclosing box sizes (moov/trak/mdia/minf/stbl grow)
            delta = len(m) - len(moov)
            for kind in (b"moov", b"trak", b"mdia", b"minf", b"stbl"):
                k = m.find(kind) - 4
                m = (m[:k] + struct.pack(
                    ">I", struct.unpack_from(">I", m, k)[0] + delta)
                    + m[k + 4:])
            return m

        dummy = patch(moov, [0] * len(samples))
        ftyp_len = base.find(b"moov") - 4
        data_start = ftyp_len + len(dummy) + 8
        offsets = []
        pos = data_start
        for s in samples:
            offsets.append(pos)
            pos += len(s)
        rebuilt = rebuild(patch(moov, offsets))
        got = demux_mp4(rebuilt)
        assert got.video.samples == samples
        assert got.num_frames == ref.num_frames
        norm = lambda s: s.replace(b"\x00\x00\x00\x01", b"|")
        assert norm(got.annexb) == norm(ref.annexb)


class TestProbeMp4:
    def test_probe_matches_content(self, tmp_path):
        frames, meta = _clip(n=12)
        stream = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        p = tmp_path / "a.mp4"
        p.write_bytes(mux_mp4(stream, meta))
        got = probe_video(str(p))
        assert (got.width, got.height) == (64, 48)
        assert got.num_frames == 12
        assert got.codec == "h264"
        assert abs(got.duration_s - 0.4) < 1e-6


@pytest.mark.skipif(not oracle.oracle_available(),
                    reason="libavcodec missing")
class TestReadVideo:
    def test_mp4_decodes_to_frames(self, tmp_path):
        frames, meta = _clip()
        stream = encode_clip_sharded(frames, meta, qp=27, gop_frames=4)
        p = tmp_path / "in.mp4"
        p.write_bytes(mux_mp4(stream, meta, audio=_fake_audio()))
        got_meta, got_frames, audio = read_video(str(p))
        assert got_meta.num_frames == len(frames)
        assert got_frames[0].y.shape == frames[0].y.shape
        assert audio is not None and len(audio.samples) == 6
        # decoded content matches what our own decoder would produce
        # (same libavcodec path the conformance tests trust): just
        # check it's close to the source at qp 27
        err = np.abs(got_frames[3].y.astype(int)
                     - frames[3].y.astype(int)).mean()
        assert err < 12.0

    def test_unsupported_ext(self, tmp_path):
        p = tmp_path / "x.mkv"
        p.write_bytes(b"")
        with pytest.raises(DecodeError):
            read_video(str(p))

    def test_mp4_to_mp4_transcode_via_executor(self, tmp_path):
        from thinvids_tpu.cluster.coordinator import Coordinator
        from thinvids_tpu.cluster.executor import LocalExecutor
        from thinvids_tpu.core.config import (
            reset_live_settings,
            update_live_settings,
        )
        from thinvids_tpu.core.status import Status

        reset_live_settings()
        try:
            frames, meta = _clip(n=8)
            stream = encode_clip_sharded(frames, meta, qp=24,
                                         gop_frames=4)
            src = tmp_path / "movie.mp4"
            src.write_bytes(mux_mp4(stream, meta, audio=_fake_audio()))

            co = Coordinator()
            for i in range(4):
                co.registry.heartbeat(f"w{i}")
            update_live_settings({"pipeline_worker_count": 4,
                                  "min_idle_workers": 0,
                                  "gop_frames": 4})
            execu = LocalExecutor(co, str(tmp_path / "out"), sync=True)
            co._launcher = execu.launch
            job = co.add_job(str(src), meta=probe_video(str(src)),
                             auto_start=True)
            job = co.store.get(job.id)
            assert job.status is Status.DONE, job.failure_reason
            out = read_mp4(job.output_path)
            assert out.num_frames == 8
            # audio rode through bit-exact
            assert out.audio is not None
            assert out.audio.samples == _fake_audio().samples
        finally:
            reset_live_settings()
