"""Distributed tracing: one coherent trace per job.

A job's run — local waves or a farm fan-out — records **spans** (name,
wall-clock start, duration, tags) into a bounded per-job ring on the
coordinator (`trace_ring_spans`). Sources:

- the wave pipeline's stage clocks (`parallel/dispatch.StageProfile`
  calls the bound recorder from every timed stage: decode / stage /
  dispatch / device_wait / fetch / sparse_unpack / unflatten / pack /
  concat, plus the SFE per-frame leg);
- the executor's per-wave spans (`wave_dispatch` / `wave_collect`);
- coordinator-side per-shard spans (ShardBoard lease → accepted part);
- remote workers: a :class:`SpanBuffer` collects the worker-side spans
  (open_source / encode / upload, plus the worker's own stage clocks)
  during a shard and ships them back over ``POST /work/spans`` with
  the job's trace id in the ``X-Tvt-Trace`` header — the coordinator
  ring then holds ONE trace spanning every host that touched the job.

Export is Chrome trace-event JSON (``GET /trace/<job>``, ``cli.py
trace <job>``) — drag into Perfetto / chrome://tracing. Every event
carries the trace id in its args; processes map to hosts and threads
to thread names, so spans nest by containment per thread exactly as
they executed.

Sampling: `trace_sample` (0..1) decides PER JOB at trace start whether
spans record at all; an unsampled job costs one dict lookup per stage.
Tracing never touches encoded bytes — output is bit-identical with
tracing on or off (parity-tested), and the bench pins the fps overhead
as ``trace_overhead_pct``.

jax-free by contract (analysis manifest).
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Iterable

from ..core.config import as_float, as_int, get_settings

#: completed jobs whose rings stay exportable (oldest evicted first) —
#: a long-lived coordinator must not accumulate every job ever traced
MAX_JOBS = 64

#: per-job ring of recent error strings (failure reasons, shard
#: failures) riding beside the spans for the flight recorder
ERROR_RING = 32

#: hard cap on spans accepted per /work/spans upload
MAX_SPANS_PER_UPLOAD = 10_000


def _now() -> float:
    return time.time()


class SpanRecorder:
    """Span sink bound to one job's trace on the local TraceStore.
    A recorder whose job was sampled out (or never started) is inert:
    `record` is a no-op and `span()` yields a nullcontext-fast path."""

    __slots__ = ("_store", "job_id", "trace_id", "host")

    def __init__(self, store: "TraceStore | None", job_id: str,
                 trace_id: str, host: str = "") -> None:
        self._store = store
        self.job_id = job_id
        self.trace_id = trace_id
        self.host = host

    @property
    def enabled(self) -> bool:
        return self._store is not None

    def record(self, name: str, t0: float, dur_s: float,
               **tags: Any) -> None:
        if self._store is None:
            return
        self._store.record_span(
            self.job_id, name, t0, dur_s, host=self.host,
            thread=threading.current_thread().name, tags=tags)

    @contextlib.contextmanager
    def span(self, name: str, **tags: Any):
        if self._store is None:
            yield
            return
        # wall clock anchors the span on the trace timeline; the
        # DURATION comes from the monotonic clock (an NTP step mid-span
        # must not produce a negative or inflated dur — same rationale
        # as StageProfile.stage's perf_counter)
        t0 = _now()
        p0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter() - p0, **tags)


#: the inert recorder handed out for unsampled/unknown jobs — shared,
#: so binding a tracer on the hot path costs one attribute read
NULL_RECORDER = SpanRecorder(None, "", "")


class SpanBuffer:
    """Worker-side span sink: collect locally during a shard, then
    ship the batch to the coordinator (``WorkerClient.upload_spans``).
    Same record/span interface as :class:`SpanRecorder`, so the
    encoder's StageProfile binds either interchangeably."""

    def __init__(self, trace_id: str, job_id: str,
                 host: str = "") -> None:
        self.trace_id = trace_id
        self.job_id = job_id
        self.host = host
        self._lock = threading.Lock()
        self._spans: list[dict[str, Any]] = []

    @property
    def enabled(self) -> bool:
        return True

    def record(self, name: str, t0: float, dur_s: float,
               **tags: Any) -> None:
        span = {"name": str(name), "t0": float(t0),
                "dur_s": float(dur_s),
                "thread": threading.current_thread().name,
                "tags": dict(tags)}
        with self._lock:
            self._spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str, **tags: Any):
        t0 = _now()
        p0 = time.perf_counter()    # monotonic duration (see
        try:                        # SpanRecorder.span)
            yield
        finally:
            self.record(name, t0, time.perf_counter() - p0, **tags)

    def drain(self) -> list[dict[str, Any]]:
        with self._lock:
            spans, self._spans = self._spans, []
            return spans


class _JobTrace:
    __slots__ = ("trace_id", "sampled", "started_at", "spans", "errors")

    def __init__(self, trace_id: str, sampled: bool, ring: int) -> None:
        self.trace_id = trace_id
        self.sampled = sampled
        self.started_at = _now()
        self.spans: deque[dict[str, Any]] = deque(maxlen=ring)
        self.errors: deque[dict[str, Any]] = deque(maxlen=ERROR_RING)


class TraceStore:
    """Per-job span rings on the coordinator. One instance per process
    (module-level :data:`TRACE`); executors start a job's trace at
    dispatch, instrumented code records through recorders, and the API
    exports Chrome trace JSON."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._jobs: "OrderedDict[str, _JobTrace]" = OrderedDict()

    # -- lifecycle -----------------------------------------------------

    def start(self, job_id: str, trace_id: str | None = None) -> str:
        """Begin a fresh trace for one job run (a restart gets a new
        trace id — its spans must not interleave with the old run's).
        Returns the trace id; "" when the job was sampled out
        (`trace_sample`)."""
        snap = get_settings()
        sample = min(1.0, max(0.0, as_float(
            snap.get("trace_sample", 1.0), 1.0)))
        ring = max(1, as_int(snap.get("trace_ring_spans", 4096), 4096))
        sampled = random.random() < sample
        trace_id = trace_id or uuid.uuid4().hex[:16]
        with self._lock:
            self._jobs[job_id] = _JobTrace(trace_id, sampled, ring)
            self._jobs.move_to_end(job_id)
            while len(self._jobs) > MAX_JOBS:
                self._jobs.popitem(last=False)
        return trace_id if sampled else ""

    def drop(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)

    def trace_id(self, job_id: str) -> str:
        """The job's current trace id ("" when absent or unsampled) —
        what the shard descriptors carry to remote workers."""
        with self._lock:
            jt = self._jobs.get(job_id)
            return jt.trace_id if jt is not None and jt.sampled else ""

    def recorder(self, job_id: str, host: str = "") -> SpanRecorder:
        """Span recorder bound to the job's live trace; the shared
        inert recorder when the job is unknown or sampled out."""
        with self._lock:
            jt = self._jobs.get(job_id)
            if jt is None or not jt.sampled:
                return NULL_RECORDER
            return SpanRecorder(self, job_id, jt.trace_id, host=host)

    # -- recording -----------------------------------------------------

    def record_span(self, job_id: str, name: str, t0: float,
                    dur_s: float, host: str = "", thread: str = "",
                    tags: dict[str, Any] | None = None,
                    trace_id: str | None = None) -> bool:
        """Append one completed span to the job's ring. With `trace_id`
        given (remote uploads), a mismatch against the job's CURRENT
        trace drops the span — a straggling worker from a superseded
        run must not pollute the new run's trace."""
        with self._lock:
            jt = self._jobs.get(job_id)
            if jt is None or not jt.sampled:
                return False
            if trace_id is not None and trace_id != jt.trace_id:
                return False
            # eviction is LRU by ACTIVITY, not by start order: a
            # long-running job keeps recording and must not lose its
            # ring because 64 short jobs dispatched after it
            self._jobs.move_to_end(job_id)
            jt.spans.append({
                "name": str(name), "t0": float(t0),
                "dur_s": max(0.0, float(dur_s)),
                "host": str(host), "thread": str(thread),
                "tags": dict(tags or {})})
            return True

    def ingest(self, job_id: str, trace_id: str,
               spans: Iterable[dict[str, Any]],
               host: str = "") -> int:
        """Record a batch of wire-form spans (the /work/spans route).
        Malformed entries are skipped; returns how many landed."""
        n = 0
        for raw in list(spans)[:MAX_SPANS_PER_UPLOAD]:
            if not isinstance(raw, dict):
                continue
            try:
                ok = self.record_span(
                    job_id, str(raw["name"]), float(raw["t0"]),
                    float(raw.get("dur_s", 0.0)),
                    host=str(raw.get("host") or host),
                    thread=str(raw.get("thread", "")),
                    tags=(raw.get("tags")
                          if isinstance(raw.get("tags"), dict) else {}),
                    trace_id=trace_id)
            except (KeyError, TypeError, ValueError):
                continue
            n += ok
        return n

    def record_error(self, job_id: str, message: str) -> None:
        with self._lock:
            jt = self._jobs.get(job_id)
            if jt is None:
                return
            self._jobs.move_to_end(job_id)     # activity-LRU, as above
            jt.errors.append({"ts": _now(), "message": str(message)})

    # -- export --------------------------------------------------------

    def snapshot(self, job_id: str) -> dict[str, Any] | None:
        """Raw trace state (spans newest-last, errors) — the flight
        recorder's source."""
        with self._lock:
            jt = self._jobs.get(job_id)
            if jt is None:
                return None
            return {"trace_id": jt.trace_id, "sampled": jt.sampled,
                    "started_at": jt.started_at,
                    "spans": list(jt.spans), "errors": list(jt.errors)}

    def export_chrome(self, job_id: str,
                      include_unsampled: bool = False
                      ) -> dict[str, Any] | None:
        """Chrome trace-event JSON (Perfetto / chrome://tracing
        loadable): one complete-event ("ph":"X") per span, µs
        timestamps, processes = hosts, threads = thread names, the
        trace id in every event's args. None when no trace exists —
        and, by default, when the job was sampled out (an empty husk
        would read as "traced, did nothing"); the flight recorder
        passes `include_unsampled` because its error ring + settings
        are worth dumping even without spans."""
        snap = self.snapshot(job_id)
        if snap is None or (not snap["sampled"]
                            and not include_unsampled):
            return None
        trace_id = snap["trace_id"]
        events: list[dict[str, Any]] = []
        pids: dict[str, int] = {}
        tids: dict[tuple[str, str], int] = {}
        for span in snap["spans"]:
            host = span["host"] or "coordinator"
            pid = pids.setdefault(host, len(pids) + 1)
            tkey = (host, span["thread"] or "main")
            tid = tids.setdefault(tkey, len(tids) + 1)
            args = {"trace_id": trace_id, "job_id": job_id}
            args.update(span["tags"])
            events.append({
                "name": span["name"], "cat": "tvt", "ph": "X",
                "ts": int(span["t0"] * 1e6),
                "dur": max(1, int(span["dur_s"] * 1e6)),
                "pid": pid, "tid": tid, "args": args})
        events.sort(key=lambda e: e["ts"])
        meta: list[dict[str, Any]] = []
        for host, pid in pids.items():
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "args": {"name": host}})
        for (host, thread), tid in tids.items():
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": pids[host], "tid": tid,
                         "args": {"name": thread}})
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace_id, "job_id": job_id,
                          "started_at": snap["started_at"],
                          "errors": snap["errors"]},
        }


#: the process-wide trace store
TRACE = TraceStore()


# ---------------------------------------------------------------------------
# ambient context (log correlation)
# ---------------------------------------------------------------------------

_TLS = threading.local()


@contextlib.contextmanager
def bind(job_id: str, trace_id: str):
    """Bind (job_id, trace_id) to the current thread for the scope —
    the structured JSON log formatter (core/log.py TVT_LOG_FORMAT=json)
    stamps these onto every line so farm logs join against traces."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = (str(job_id), str(trace_id))
    try:
        yield
    finally:
        _TLS.ctx = prev


def current_ids() -> tuple[str, str] | None:
    """(job_id, trace_id) bound to this thread, or None."""
    return getattr(_TLS, "ctx", None)
