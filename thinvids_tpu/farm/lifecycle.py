"""Worker lifecycle states for the elastic farm.

The reference farm breathes: agents suspend idle Wyse nodes and the
manager wakes them with WoL magic packets (SURVEY §L6). The repro's
analog is an explicit, model-checked state machine driven by the
capacity controller (farm/controller.py):

    ACTIVE ──drain──▶ DRAINING ──leases empty──▶ SUSPENDED
      ▲                  │                          │
      │◀────undrain──────┘                        wake
      │                                             ▼
      └───────heartbeat / claim────────────────  WAKING

A DRAINING worker finishes its in-flight shards but stops claiming
(``ShardBoard.claim`` consults the controller); its suspend fires only
once its lease set is empty. A WAKING worker becomes ACTIVE the moment
it proves itself up (a heartbeat or a claim); a wake that never lands
falls back to SUSPENDED so the controller can retry. A SUSPENDED host
that heartbeats on its own (operator-started) rejoins directly.

The transition table is DECLARED in analysis/manifest.py
(``WORKER_MACHINE``) — every ``lifecycle`` write site is audited
(TVT-M001) and the bounded explorer model-checks the protocol against
the shard board (TVT-M002: no shard is ever assigned to a
DRAINING/SUSPENDED worker, and drain never strands a lease).

jax-free by contract: the whole farm/ package runs on coordinator
control-plane threads.
"""

from __future__ import annotations

import enum


class WorkerState(str, enum.Enum):
    ACTIVE = "active"        # claim-capable, counted as farm capacity
    DRAINING = "draining"    # finishing in-flight shards; claims refused
    SUSPENDED = "suspended"  # powered down / scaled to zero
    WAKING = "waking"        # wake fired; waiting for the first heartbeat

    @property
    def may_claim(self) -> bool:
        """True for the one state the ShardBoard may lease work to.
        (WAKING workers are promoted to ACTIVE by the claim itself —
        a claim is proof the worker is up.)"""
        return self is WorkerState.ACTIVE

    @property
    def is_on(self) -> bool:
        """True while the host consumes power/worker-seconds (the
        ``farm_active_worker_s`` accounting input): everything except
        SUSPENDED."""
        return self is not WorkerState.SUSPENDED
